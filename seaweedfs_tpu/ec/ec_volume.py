"""EcVolume: a mounted set of local EC shards serving needle reads.

Behavioral match of reference weed/storage/erasure_coding/ec_volume.go,
ec_shard.go, ec_volume_delete.go and the local parts of store_ec.go:

  * shards are .ec00-.ec13 files mounted individually (a node usually
    holds a few of the 14);
  * needle lookup binary-searches the sorted .ecx
    (SearchNeedleFromSortedIndex, ec_volume.go:199) and maps the .dat
    span to per-shard intervals via the striping math (locate.py);
  * reads serve each interval from a local shard when present, else
    reconstruct that interval from any 10 available shards through the
    codec (store_ec.go:178-209 / recoverOneRemoteEcShardInterval —
    remote fan-in arrives with the data-plane server; the `fetch`
    callback is that seam);
  * deletes tombstone the .ecx entry in place and append the needle id
    to the .ecj journal (DeleteNeedleFromEcx).

The shard-size → .dat-size derivation uses the reference's row-count
quirk baked into locate.py (large rows recoverable from shard size).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Optional

import numpy as np

from seaweedfs_tpu import trace
from seaweedfs_tpu.ec import ec_files, locate, repair_session
from seaweedfs_tpu.ec.tile_cache import TileCache
from seaweedfs_tpu.ec.codec import ReedSolomon, new_encoder
from seaweedfs_tpu.qos.singleflight import SingleFlight
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle, get_actual_size
from seaweedfs_tpu.storage.needle_map import SortedNeedleMap
from seaweedfs_tpu.storage.volume import NeedleNotFound, volume_base_name
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util import durable

# fetch(shard_id, offset, size) -> bytes | None. Returning None means
# the shard is unavailable everywhere (candidates exhausted).
ShardFetcher = Callable[[int, int, int], Optional[bytes]]

# staging cap for a tile-batched degraded decode: one leader decodes at
# most this many contiguous cold tiles in one gather + dispatch (32 x
# the 256 KiB default tile = 8 MiB of survivor staging per run — big
# enough that a whole-object degraded GET is one dispatch, small enough
# that k x run of survivor bytes stays cache-friendly)
_DECODE_RUN_TILES = 32


class NotEnoughShards(RuntimeError):
    pass


class ShardTruncated(RuntimeError):
    """A local shard file is shorter than its nominal length (disk
    truncation/corruption). Reads treat the shard as lost and
    reconstruct from the survivors instead of serving zero-fill."""


class RemoteEcAttachment:
    """A tiered EC volume's remote half: which backend holds which
    shards, persisted as the `.evf` sidecar next to the (local) .ecx.

    Remote shards are deliberately NOT EcVolumeShard mounts: the
    quarantine machinery is path/file-based and a transient backend
    error must degrade to reconstruction, never permanently quarantine
    a perfectly good remote object."""

    def __init__(self, backend_name: str, shard_size: int, shards: dict[int, dict]):
        self.backend_name = backend_name  # "dir.default" / "s3.default"
        self.shard_size = int(shard_size)  # nominal per-shard length
        # shard id -> {"key": str, "size": int}
        self.shards = {int(k): dict(v) for k, v in shards.items()}

    def to_json(self) -> dict:
        return {
            "backend": self.backend_name,
            "shard_size": self.shard_size,
            "shards": {str(k): v for k, v in sorted(self.shards.items())},
        }

    @classmethod
    def from_json(cls, doc: dict) -> "RemoteEcAttachment":
        return cls(doc["backend"], doc["shard_size"], doc.get("shards", {}))


class EcVolumeShard:
    """One local .ec?? file (ec_shard.go:15)."""

    def __init__(self, directory: str, vid: int, shard_id: int, collection: str = ""):
        self.volume_id = vid
        self.shard_id = shard_id
        self.collection = collection
        self.path = volume_base_name(directory, collection, vid) + ec_files.to_ext(
            shard_id
        )
        self._f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)

    def read_at(self, offset: int, size: int) -> bytes:
        try:
            # pread is positionless: concurrent handler threads share
            # this fd safely (seek+read would interleave positions and
            # misread healthy shards under the ThreadingHTTPServer)
            data = os.pread(self._f.fileno(), size, offset)
        except (OSError, ValueError):
            # fd closed by a concurrent quarantine/unmount — from this
            # reader's view the shard is gone; treat it as lost so the
            # caller falls through to remote fetch / reconstruction
            raise ShardTruncated(
                f"shard {self.shard_id} of vid {self.volume_id}: "
                f"closed during read [{offset}, {offset + size})"
            ) from None
        if len(data) < size:
            # encode materializes zero padding on disk, so every shard
            # file spans the full nominal length — a short read means
            # the file was truncated/corrupted, never legitimate tail
            try:
                on_disk = os.path.getsize(self.path)
            except OSError:
                on_disk = -1  # renamed away by a racing quarantine
            raise ShardTruncated(
                f"shard {self.shard_id} of vid {self.volume_id}: "
                f"read [{offset}, {offset + size}) past file end "
                f"({on_disk} bytes)"
            )
        return data

    def close(self) -> None:
        self._f.close()

    def destroy(self) -> None:
        self.close()
        os.remove(self.path)


class EcVolume:
    def __init__(
        self,
        directory: str,
        vid: int,
        collection: str = "",
        backend: str | None = None,
    ):
        self.volume_id = vid
        self.collection = collection
        self.directory = directory
        self.base_name = volume_base_name(directory, collection, vid)
        self.shards: dict[int, EcVolumeShard] = {}
        self._ecx: SortedNeedleMap | None = None
        self._ecx_version = 0  # bumped on deletes to refresh the mmap
        # codec backend for degraded-read reconstruction (the `ec.codec`
        # config, threaded down from the server; None = process default)
        self.backend = backend
        self._rs: ReedSolomon | None = None
        self.version = 3
        # health-tiered shard-location cache (store_ec.go:218-259):
        # the serving layer fills this from the master's LookupEcVolume
        # and forgets locations whose reads fail
        self.shard_locations: dict[int, list[str]] = {}
        self.shard_locations_lock = threading.Lock()
        self.shard_locations_refresh_time = 0.0
        # serializes quarantine decisions so only one thread verifies
        # and unmounts a suspect shard
        self._quarantine_lock = threading.Lock()
        # shard id → reason for every shard quarantined on this node
        # (scrub-plane surface: rides heartbeats + /status JSON)
        self.quarantined: dict[int, str] = {}
        # shard id → consecutive verified-full-size read failures: at 3
        # the shard is a failing medium (EIO) and gets quarantined so
        # repair regenerates it (chaos hardening, see _read_interval).
        # Only double failures that size-verification cleared count, so
        # transient close/remount races never accumulate here.
        self._read_error_strikes: dict[int, int] = {}
        # wired by the Store to its quarantine registry so the event
        # reaches the heartbeat loop (forced delta beat) immediately
        self.on_quarantine: Callable[[int, int, str], None] | None = None
        # degraded-read fast path (docs/SCRUB.md): reconstructed tiles
        # are cached per volume — decode once, serve later degraded
        # GETs from memory (the decode rows for a (survivors, target)
        # pair are cached on the codec itself, rs.decode_rows)
        self.tile_cache = TileCache()
        # singleflight for tile decodes: N concurrent degraded GETs of
        # one hot uncached tile must not fan out N× k-shard gathers
        self._decode_flight = SingleFlight()
        # lifecycle tiering (docs/TIERING.md): shards this node moved to
        # an object-store backend, readable via ranged sub-shard GETs
        self.remote: RemoteEcAttachment | None = None

    # --- mounting (disk_location_ec.go) ---
    @classmethod
    def load(
        cls,
        directory: str,
        vid: int,
        collection: str = "",
        backend: str | None = None,
    ) -> "EcVolume":
        ev = cls(directory, vid, collection, backend=backend)
        for shard_id in range(ec_files.TOTAL_SHARDS):
            path = ev.base_name + ec_files.to_ext(shard_id)
            if os.path.exists(path):
                ev.mount_shard(shard_id)
        if not os.path.exists(ev.base_name + ".ecx"):
            raise FileNotFoundError(ev.base_name + ".ecx")
        ev.load_remote()
        return ev

    def mount_shard(self, shard_id: int) -> None:
        if shard_id not in self.shards:
            self.shards[shard_id] = EcVolumeShard(
                self.directory, self.volume_id, shard_id, self.collection
            )
            # a freshly (re)mounted shard file is a repaired one: the
            # rebuild path wrote a new full-length file at this path.
            # The pop takes the quarantine lock: an admin remount racing
            # a scrub thread's quarantine decision must serialize, or
            # the marker for a shard quarantined mid-mount is lost
            # (weedlint unguarded-write finding, OPERATIONS.md round 9)
            with self._quarantine_lock:
                self.quarantined.pop(shard_id, None)
            # a remounted shard is a REPAIRED one: cached tiles were
            # decoded against the pre-repair survivor set — drop them
            self.tile_cache.invalidate()

    def unmount_shard(self, shard_id: int) -> None:
        # deliberately does NOT close the shard's fd: handler threads
        # may hold a reference and be mid-pread — closing here would at
        # best EBADF them and at worst recycle the fd number into an
        # unrelated open() whose bytes pread would then silently serve
        # as shard data. The file object closes when the last reference
        # (this dict's or a reader's local) is dropped.
        self.shards.pop(shard_id, None)

    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    # --- remote tier attachment (docs/TIERING.md) ---
    @property
    def evf_path(self) -> str:
        return self.base_name + ".evf"

    def serving_shard_ids(self) -> list[int]:
        """Shards this node can serve: local mounts plus tiered remote
        shards. This is what rides the heartbeat's ec_index_bits — a
        fully tiered volume must keep routing here (and must NOT look
        missing to the repair scheduler)."""
        ids = set(self.shards)
        if self.remote is not None:
            ids |= set(self.remote.shards)
        return sorted(ids)

    def load_remote(self) -> None:
        """Adopt an existing .evf sidecar (startup / remount)."""
        try:
            with open(self.evf_path, "rb") as f:
                self.remote = RemoteEcAttachment.from_json(json.load(f))
        except FileNotFoundError:
            self.remote = None
        except (OSError, ValueError, KeyError) as e:
            wlog.warning("ec vid %d: unreadable .evf (%s); ignoring", self.volume_id, e)
            self.remote = None

    def attach_remote(self, attachment: RemoteEcAttachment) -> None:
        """Durably publish the .evf sidecar, then serve through it.
        Crash ordering: before the publish, local shards are still the
        only truth (remote copies are orphans a re-run re-uploads);
        after it, reads resolve remotely even once local files go."""
        tmp = self.evf_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps(attachment.to_json(), indent=1).encode())
        durable.publish(tmp, self.evf_path)
        self.remote = attachment

    def detach_remote(self) -> RemoteEcAttachment | None:
        """Drop the .evf (tier-in complete: local shards are back).
        Returns the old attachment so the caller can delete the remote
        objects best-effort AFTER the detach is durable."""
        old = self.remote
        self.remote = None
        try:
            os.remove(self.evf_path)
            durable.fsync_dir(self.directory)
        except OSError:
            pass
        return old

    def _remote_fetch(self, shard_id: int, offset: int, size: int) -> bytes | None:
        """Ranged sub-shard read against the attached backend; None on
        any failure (the caller falls through to peer fetch and
        reconstruction — a flaky backend degrades, never faults)."""
        remote = self.remote
        if remote is None:
            return None
        info = remote.shards.get(shard_id)
        if info is None:
            return None
        from seaweedfs_tpu.stats.metrics import (
            TIER_REMOTE_READ_ERRORS,
            TIER_REMOTE_READS,
        )
        from seaweedfs_tpu.storage import backend as bk

        backend = bk.get_backend(remote.backend_name)
        if backend is None:
            TIER_REMOTE_READ_ERRORS.inc()
            wlog.warning(
                "ec vid %d: tier backend %s not configured",
                self.volume_id, remote.backend_name,
            )
            return None
        try:
            data = backend.new_storage_file(
                info["key"], int(info.get("size", remote.shard_size))
            ).read_at(size, offset)
        except Exception as e:  # noqa: BLE001 — any backend fault degrades
            TIER_REMOTE_READ_ERRORS.inc()
            wlog.warning(
                "ec vid %d shard %d: tier read [%d,%d) failed: %s",
                self.volume_id, shard_id, offset, offset + size, e,
            )
            return None
        if len(data) != size:
            TIER_REMOTE_READ_ERRORS.inc()
            return None
        TIER_REMOTE_READS.inc()
        return data

    def _with_remote(self, fetch: ShardFetcher | None) -> ShardFetcher | None:
        """Interpose the tier backend ahead of the peer-fetch seam:
        tiered shards resolve with one ranged backend GET; on a miss or
        backend fault the original fetch (peer fan-in) still runs, and
        reconstruction candidates go through the same wrapper."""
        if self.remote is None:
            return fetch

        def wrapped(shard_id: int, offset: int, size: int) -> bytes | None:
            data = self._remote_fetch(shard_id, offset, size)
            if data is not None:
                return data
            if fetch is not None:
                return fetch(shard_id, offset, size)
            return None

        return wrapped

    @property
    def rs(self) -> ReedSolomon:
        if self._rs is None:
            self._rs = new_encoder(backend=self.backend)
        return self._rs

    # --- index ---
    def _ecx_map(self) -> SortedNeedleMap:
        if self._ecx is None:
            self._ecx = SortedNeedleMap.load(self.base_name + ".ecx")
        return self._ecx

    def locate_needle(self, needle_id: int) -> tuple[int, int]:
        """(dat offset, stored size) via .ecx binary search; raises
        NeedleNotFound for missing or tombstoned ids."""
        nv = self._ecx_map().search(needle_id)
        if nv is None:
            raise NeedleNotFound(f"needle {needle_id} not in ec volume")
        if nv.size == t.TOMBSTONE_FILE_SIZE:
            raise NeedleNotFound(f"needle {needle_id} deleted")
        return nv.actual_offset, nv.size

    def dat_file_size(self) -> int:
        """Original .dat size derived from the shard size via the
        row-count quirk (shard = nLarge·large + nSmall·small; we only
        need a dat_size that reproduces the same row split).

        Uses the MAX across mounted shards: intact shards all share the
        nominal length, while a truncated one is shorter — deriving
        geometry from it would mis-split rows and corrupt the interval
        mapping for every shard. A fully tiered volume has zero local
        shards; its geometry comes from the .evf attachment."""
        if not self.shards:
            if self.remote is not None:
                shard_size = self.remote.shard_size
            else:
                raise NotEnoughShards("no local shards mounted")
        else:
            shard_size = max(s.size for s in self.shards.values())
        large, small = locate.LARGE_BLOCK_SIZE, locate.SMALL_BLOCK_SIZE
        n_large = shard_size // large
        n_small = (shard_size - n_large * large) // small
        # any size in the row span maps identically; use the row capacity
        return n_large * large * locate.DATA_SHARDS + n_small * small * locate.DATA_SHARDS

    # --- reads (store_ec.go:119 ReadEcShardNeedle) ---
    def read_needle(
        self, needle_id: int, fetch: ShardFetcher | None = None
    ) -> Needle:
        offset, size = self.locate_needle(needle_id)
        span = get_actual_size(size, self.version)
        blob = self.read_span(offset, span, fetch)
        return Needle.from_bytes(blob, self.version, size=size)

    def read_span(
        self, offset: int, size: int, fetch: ShardFetcher | None = None
    ) -> bytes:
        fetch = self._with_remote(fetch)
        dat_size = self.dat_file_size()
        out = bytearray()
        for iv in locate.locate_data(
            locate.LARGE_BLOCK_SIZE, locate.SMALL_BLOCK_SIZE, dat_size, offset, size
        ):
            shard_id, shard_off = iv.to_shard_id_and_offset()
            out += self._read_interval(shard_id, shard_off, iv.size, fetch)
        return bytes(out)

    def quarantine_shard(self, shard_id: int, reason: str) -> bool:
        """Quarantine a shard this node holds: unmount it (every later
        read treats it as lost — remote fetch first, reconstruction
        fallback) AND rename its file to `<shard>.bad` so the rebuild
        path sees it as MISSING and regenerates it — an unmount alone
        would leave a full-length corrupt file that shard_presence()
        counts as present, silently skipping the regeneration (and a
        restart would remount it). The rename is safe under concurrent
        preads: open fds follow the inode, so in-flight reads of other
        (healthy) interleavings finish normally. Returns True when the
        shard was quarantined by THIS call."""
        with self._quarantine_lock:
            shard = self.shards.get(shard_id)
            if shard is None:
                return False  # not mounted (or already quarantined)
            self.unmount_shard(shard_id)
            try:
                os.replace(shard.path, shard.path + ".bad")
                # dir fsync: the quarantine decision must survive a
                # crash — a resurrected corrupt shard would be remounted
                # at restart and silently skip regeneration (rebuild
                # keys off the shard file being MISSING)
                durable.fsync_dir(self.directory)
            except OSError:
                pass  # vanished/unwritable dir: unmount still protects
            self.quarantined[shard_id] = reason
        self.tile_cache.invalidate()
        cb = self.on_quarantine
        if cb is not None:
            # outside the lock: the callback pokes the heartbeat loop
            cb(self.volume_id, shard_id, reason)
        return True

    def _quarantine_if_truncated(self, shard_id: int) -> bool:
        """Quarantine a suspect shard only after re-verifying the
        on-disk file really is shorter than its nominal length (a short
        pread can also mean the fd was closed under us, or a racing
        replace). Serialized so concurrent failing readers don't
        double-close. Returns True when the shard is quarantined (or
        already gone)."""
        with self._quarantine_lock:
            shard = self.shards.get(shard_id)
            if shard is None:
                return True  # another thread already quarantined it
            try:
                actual = os.path.getsize(shard.path)
            except OSError:
                actual = -1  # file vanished: certainly not servable
            # nominal length comes from the siblings (every intact shard
            # of a volume shares it — the dat_file_size derivation), not
            # from this shard's own mount-time size: a shard mounted
            # already-truncated would otherwise equal its own "nominal"
            # and never be evicted
            nominal = max(s.size for s in self.shards.values())
            if actual >= nominal:
                return False
            # self-heal beyond the reference: quarantine the corrupt
            # shard so this and every later read treats it exactly like
            # a lost shard, and its short length can never poison
            # dat_file_size()'s geometry
            wlog.warning(
                "ec read: shard %d of vid %d is %d bytes, nominal %d; "
                "quarantining",
                shard_id, self.volume_id, actual, nominal,
            )
            self.unmount_shard(shard_id)
            try:
                os.replace(shard.path, shard.path + ".bad")
                # same dir-fsync contract as quarantine_shard above
                durable.fsync_dir(self.directory)
            except OSError:
                pass
            reason = f"truncated: {actual} bytes, nominal {nominal}"
            self.quarantined[shard_id] = reason
        self.tile_cache.invalidate()
        cb = self.on_quarantine
        if cb is not None:
            cb(self.volume_id, shard_id, reason)
        return True

    def _read_interval(
        self, shard_id: int, offset: int, size: int, fetch: ShardFetcher | None
    ) -> bytes:
        shard = self.shards.get(shard_id)
        if shard is not None:
            data = None
            try:
                data = shard.read_at(offset, size)
            except ShardTruncated as e:
                if not self._quarantine_if_truncated(shard_id):
                    # healthy full-size file: the failure was transient
                    # (racing close+remount, or interleaved replace) —
                    # one retry against the current mount
                    cur = self.shards.get(shard_id)
                    if cur is not None:
                        try:
                            data = cur.read_at(offset, size)
                        except ShardTruncated:
                            # still verify before evicting: a second
                            # transient race must not permanently
                            # quarantine a healthy on-disk shard
                            if not self._quarantine_if_truncated(shard_id):
                                # full-size file that still won't read:
                                # a failing medium (EIO), not a race.
                                # Three CONSECUTIVE strikes quarantine
                                # it so the repair plane regenerates
                                # the shard instead of every future
                                # read paying retry+reconstruct forever
                                # — the weedchaos EIO scenario's
                                # required behavior (quarantine, don't
                                # crash)
                                strikes = self._read_error_strikes
                                strikes[shard_id] = strikes.get(shard_id, 0) + 1
                                if strikes[shard_id] >= 3:
                                    strikes.pop(shard_id, None)
                                    self.quarantine_shard(
                                        shard_id,
                                        f"persistent read errors: {e}",
                                    )
                if data is None:
                    wlog.warning("ec read: %s; falling back to recovery", e)
            if data is not None:
                # a clean read clears the strike count: the counter
                # tracks CONSECUTIVE failures, so rare transient races
                # spread over weeks can never add up to a quarantine
                # of a healthy shard
                if self._read_error_strikes:
                    self._read_error_strikes.pop(shard_id, None)
                return data
        if self.tile_cache.covers(shard_id, offset, size):
            # a prior degraded read already decoded this range: memory
            # beats even a healthy remote shard fetch
            return self._reconstruct_interval(shard_id, offset, size, fetch)
        if fetch is not None:
            data = fetch(shard_id, offset, size)
            if data is not None:
                return data
        return self._reconstruct_interval(shard_id, offset, size, fetch)

    def _nominal_shard_len(self) -> int:
        """Full per-shard byte length (every intact shard of a volume
        shares it — see dat_file_size)."""
        if not self.shards:
            if self.remote is not None:
                return self.remote.shard_size
            raise NotEnoughShards("no local shards mounted")
        return max(s.size for s in self.shards.values())

    def _reconstruct_interval(
        self, target_shard: int, offset: int, size: int, fetch: ShardFetcher | None
    ) -> bytes:
        """Serve a degraded interval, decoding whole cache tiles so the
        k-shard gather runs once per tile instead of once per GET —
        and decoding contiguous RUNS of uncached tiles in ONE
        gather + decode dispatch: a GET spanning M cold tiles used to
        round-trip the survivor gather and the codec M times; now the
        leader stages the whole run's survivor span once, decodes it
        in one dispatch (bytewise RS: a span decode IS the per-tile
        decodes concatenated), and feeds the tile cache in bulk.
        Freshly decoded tiles are donated to an in-progress rebuild of
        the same shard (repair piggyback, docs/SCRUB.md)."""
        from seaweedfs_tpu.stats.metrics import EC_DEGRADED_READS

        EC_DEGRADED_READS.inc()
        cache = self.tile_cache
        if not cache.enabled:
            return self._reconstruct_range(target_shard, offset, size, fetch)
        tile = cache.tile_bytes
        try:
            shard_len = self._nominal_shard_len()
        except NotEnoughShards:
            # every local shard vanished under us (concurrent
            # quarantine drained self.shards mid-read): exact-interval
            # reconstruction needs no local geometry — the remote
            # gather can still find k survivors
            return self._reconstruct_range(target_shard, offset, size, fetch)
        sess = repair_session.find(self.volume_id)
        out = bytearray()
        pos = offset
        end = offset + size
        while pos < end:
            t_off = (pos // tile) * tile
            data = cache.get(target_shard, t_off)
            owned: list[tuple[int, threading.Event]] = []
            if data is None:
                # singleflight: exactly one thread decodes a given tile;
                # the rest wait on its event and re-probe the cache —
                # without this, N concurrent GETs of one hot uncached
                # tile fan out N× the k-shard gather and N decodes
                key = (target_shard, t_off)
                lease = self._decode_flight.lead(key)
                if lease is not None:
                    owned.append((t_off, lease))
                else:
                    self._decode_flight.wait(key, timeout=30.0)
                    data = cache.get(target_shard, t_off)
                    # a miss here means the leader failed (or the cache
                    # evicted/invalidated): decode for ourselves below,
                    # WITHOUT re-registering — correctness never depends
                    # on the singleflight, only the stampede width does
            if data is None and not owned:
                t_len = min(tile, shard_len - t_off)
                gen = cache.invalidations
                data = self._reconstruct_range(
                    target_shard, t_off, t_len, fetch
                )
                if cache.put(target_shard, t_off, data, gen=gen) and (
                    sess is not None
                ):
                    sess.donate(target_shard, t_off, data)
            elif data is None:
                # this thread leads tile t_off: extend leadership over
                # the following uncached tiles this interval still
                # needs (stopping at a cache hit, another leader, the
                # shard tail, or the staging cap) — the whole run then
                # costs ONE survivor gather and ONE decode dispatch
                run_lim = min(shard_len, -(-end // tile) * tile)
                nxt = t_off + tile
                while nxt < run_lim and len(owned) < _DECODE_RUN_TILES:
                    if cache.get(target_shard, nxt) is not None:
                        break
                    lease = self._decode_flight.lead((target_shard, nxt))
                    if lease is None:
                        break
                    owned.append((nxt, lease))
                    nxt += tile
                run_len = min(nxt, shard_len) - t_off
                if run_len <= 0:
                    self._release_decode_leases(target_shard, owned)
                    raise NotEnoughShards(
                        f"vid {self.volume_id}: shard {target_shard} "
                        f"interval [{offset}, {end}) past shard length"
                    )
                # capture the invalidation generation BEFORE the gather:
                # a quarantine landing mid-decode may mean a survivor we
                # already read was corrupt — the stale result must not
                # be cached or donated (put() checks gen under the lock
                # invalidate() increments under)
                gen = cache.invalidations
                try:
                    run = self._reconstruct_range(
                        target_shard, t_off, run_len, fetch
                    )
                finally:
                    # wake waiters of every owned tile, win or lose
                    self._release_decode_leases(target_shard, owned)
                for j, (o_off, _) in enumerate(owned):
                    chunk = run[j * tile : min((j + 1) * tile, run_len)]
                    if not chunk:
                        break
                    if cache.put(target_shard, o_off, chunk, gen=gen) and (
                        sess is not None
                    ):
                        # piggyback: this tile is exactly what the
                        # rebuild writer needs at this offset — serving
                        # traffic makes repair forward-progress instead
                        # of duplicating its reads. Gated on the same
                        # gen check as the insert; the residual window
                        # between put and donate is backstopped by the
                        # scrub plane's parity sweep of the rebuilt
                        # shard.
                        sess.donate(target_shard, o_off, chunk)
                take = min(end, t_off + run_len) - pos
                if take <= 0:
                    raise NotEnoughShards(
                        f"vid {self.volume_id}: shard {target_shard} "
                        f"interval [{offset}, {end}) past reconstructed "
                        f"length"
                    )
                out += run[pos - t_off : pos - t_off + take]
                pos += take
                continue
            take = min(end, t_off + len(data)) - pos
            if take <= 0:  # cached tail tile shorter than the request
                raise NotEnoughShards(
                    f"vid {self.volume_id}: shard {target_shard} interval "
                    f"[{offset}, {end}) past reconstructed length"
                )
            out += data[pos - t_off : pos - t_off + take]
            pos += take
        return bytes(out)

    def _release_decode_leases(
        self, target_shard: int, owned: list[tuple[int, "threading.Event"]]
    ) -> None:
        """Unregister this thread's singleflight leases and wake their
        waiters (who re-probe the cache and self-serve on a miss)."""
        for o_off, ev in owned:
            self._decode_flight.release((target_shard, o_off), ev)

    def donate_cached_tiles(self, sess) -> int:
        """Seed a just-opened rebuild session with every resident tile
        of its target shards: degraded traffic that ALREADY ran still
        makes repair forward-progress. Returns tiles donated."""
        donated = 0
        for target in sess.targets:
            for t_off, data in self.tile_cache.snapshot(target):
                if sess.donate(target, t_off, data):
                    donated += 1
        return donated

    def _reconstruct_range(
        self, target_shard: int, offset: int, size: int, fetch: ShardFetcher | None
    ) -> bytes:
        """Rebuild one shard range from any k shards: local survivors
        first, then a first-k-wins race over ALL remote candidates on
        the shared qos.hedge attempt pool (docs/QOS.md — the degraded
        analogue of hedged replica reads; the old serial/per-call-pool
        gather waited on every straggler)."""
        k = self.rs.data_shards
        total = self.rs.total_shards
        sess = repair_session.find(self.volume_id)
        if sess is not None:
            sess.serving_enter()
        try:
            with trace.span(
                "ec.degraded", plane="serve", nbytes=size
            ) as sp:
                shards: list[Optional[np.ndarray]] = [None] * total
                available = 0
                # snapshot: mount/unmount RPCs mutate self.shards
                for sid, local in list(self.shards.items()):
                    if sid == target_shard:
                        continue
                    if available >= k:
                        break  # the decode uses the first k survivors
                    try:
                        shards[sid] = np.frombuffer(
                            local.read_at(offset, size), dtype=np.uint8
                        )
                    except ShardTruncated as e:
                        wlog.warning("ec rebuild: %s", e)
                        self._quarantine_if_truncated(sid)
                        continue  # a corrupt survivor counts as missing
                    available += 1
                if fetch is not None and available < k:
                    candidates = [
                        sid
                        for sid in range(total)
                        if shards[sid] is None and sid != target_shard
                    ]

                    def attempt(done, sid):
                        if done.is_set():
                            return None  # k winners already in
                        data = fetch(sid, offset, size)
                        if data is None or len(data) != size:
                            return None
                        return data

                    from seaweedfs_tpu.qos import hedge

                    got = hedge.gather_first_k(
                        {
                            sid: (lambda done, s=sid: attempt(done, s))
                            for sid in candidates
                        },
                        k - available,
                    )
                    for sid, raw in got.items():
                        shards[sid] = np.frombuffer(raw, dtype=np.uint8)
                        available += 1
                if available < k:
                    raise NotEnoughShards(
                        f"vid {self.volume_id}: only {available} of {k} "
                        f"shards reachable to rebuild shard {target_shard}"
                    )
                survivors = tuple(
                    i for i, s in enumerate(shards) if s is not None
                )[:k]
                # decode rows cached on the codec: inverted once per
                # (survivors, target), not per interval
                rows = self.rs.decode_rows(survivors, (target_shard,))
                stacked = np.stack([shards[i] for i in survivors])
                rebuilt = self.rs._apply(rows, stacked)
                if sp:
                    sp.annotate("vid", self.volume_id)
                    sp.annotate("shard", target_shard)
                return rebuilt[0].tobytes()
        finally:
            if sess is not None:
                sess.serving_exit()

    # --- deletes (ec_volume_delete.go) ---
    def delete_needle(self, needle_id: int) -> None:
        """Tombstone the .ecx entry in place + journal to .ecj."""
        m = self._ecx_map()
        i = m.entry_index(needle_id)
        if i < 0:
            return
        if int(m.sizes[i]) == t.TOMBSTONE_FILE_SIZE:
            return
        entry_off = i * idx_codec.ENTRY_SIZE + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE
        with open(self.base_name + ".ecx", "r+b") as f:
            f.seek(entry_off)
            f.write((t.TOMBSTONE_FILE_SIZE).to_bytes(4, "big"))
        m.sizes[i] = t.TOMBSTONE_FILE_SIZE
        with open(self.base_name + ".ecj", "ab") as f:
            f.write(t.needle_id_to_bytes(needle_id))

    # --- lifecycle ---
    def close(self) -> None:
        for shard in self.shards.values():
            shard.close()
        self.shards.clear()

    def destroy(self) -> None:
        self.close()
        for shard_id in range(ec_files.TOTAL_SHARDS):
            p = self.base_name + ec_files.to_ext(shard_id)
            for path in (p, p + ".bad"):  # .bad = quarantined forensic copy
                if os.path.exists(path):
                    os.remove(path)
        for ext in (".ecx", ".ecj", ".evf"):
            p = self.base_name + ext
            if os.path.exists(p):
                os.remove(p)
