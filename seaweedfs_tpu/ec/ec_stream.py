"""Double-buffered host↔HBM streaming drivers for EC encode/rebuild.

The classic drivers in ec_files.py are synchronous: read a batch,
round-trip it through the codec, write, repeat — every stage waits for
every other. These drivers pipeline the stages the TPU-first way
(SURVEY §7 step 2 "streaming driver double-buffers tiles host↔HBM"),
matching the *output bytes* of ec_files.py exactly while overlapping:

  disk read (tile t+1)  ‖  H2D + SWAR kernel (tile t)  ‖  parity D2H +
  file writes (tile t-1)

The host side is a three-thread pipeline: a reader thread fills a
bounded tile queue from disk, the caller's thread dispatches the codec
(JAX dispatch is async — `device_put` and the encode call return
immediately), and a writer thread blocks on the parity fetch and lands
all 14 shard files. So disk reads, device compute, and file writes
genuinely overlap even though the fetch is blocking — on a local-PCIe
TPU host the pipeline is no longer capped by one thread's read+write
rate. Only the [4, N] parity ever crosses device→host — the ten
data-shard files are byte copies of the blocks read from the .dat,
written straight from the host buffer. The single writer thread
preserves tile order (queue FIFO), so output bytes stay identical to
the synchronous ec_files.py drivers.

Role match: the 256 KB-batch loops at reference
weed/storage/erasure_coding/ec_encoder.go:188-225 (encodeDatFile) and
:227-281 (rebuildEcFiles), rebuilt as a pipelined driver.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable

import numpy as np

from seaweedfs_tpu.ec import locate

DATA_SHARDS = locate.DATA_SHARDS
PARITY_SHARDS = locate.PARITY_SHARDS
TOTAL_SHARDS = locate.TOTAL_SHARDS
LARGE_BLOCK_SIZE = locate.LARGE_BLOCK_SIZE
SMALL_BLOCK_SIZE = locate.SMALL_BLOCK_SIZE

# Per-shard bytes per pipelined tile. 16 MiB x 10 shards = 160 MiB of
# host buffer per in-flight stage.
DEFAULT_TILE_BYTES = 16 * 1024 * 1024
# Dispatched-but-unfetched tiles queued toward the writer thread; with
# the 1-deep read queue and the tile in the dispatcher's hands, at most
# _INFLIGHT + 2 tiles of host memory are live.
_INFLIGHT = 2

_EOF = object()  # end-of-stream marker flowing through the queues
_STOPPED = object()  # returned by _q_get when the pipeline aborted

_Q_TICK = 0.2  # seconds between stop-flag checks while blocked


def _q_put(q: queue.Queue, item, stop: threading.Event) -> bool:
    """put() that gives up when the pipeline aborts (a dead consumer
    must not leave the producer blocked forever)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=_Q_TICK)
            return True
        except queue.Full:
            continue
    return False


def _q_get(q: queue.Queue, stop: threading.Event):
    while not stop.is_set():
        try:
            return q.get(timeout=_Q_TICK)
        except queue.Empty:
            continue
    return _STOPPED


class _Pipeline:
    """Reader + writer threads around the caller's dispatch loop, with
    first-error propagation and deadlock-free shutdown."""

    def __init__(self):
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self._threads: list[threading.Thread] = []

    def spawn(self, fn) -> None:
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised on join
                self.errors.append(e)
                self.stop.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._threads.append(t)

    def finish(self, caller_error: bool = False) -> None:
        """Join the stage threads; re-raise the first stage error."""
        if caller_error:
            self.stop.set()
        for t in self._threads:
            t.join()
        if not caller_error and self.errors:
            raise self.errors[0]


def stream_write_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    parity_fn: Callable[[np.ndarray], "object"] | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
) -> None:
    """Pipelined .dat → .ec00…13, byte-identical to write_ec_files.

    parity_fn([10, step] u8 host tile) must *dispatch* the parity
    computation and return an opaque handle immediately; fetch_fn turns
    the handle into a [4, step] u8 numpy array (blocking). The defaults
    run the SWAR kernel on the attached TPU. The indirection keeps the
    pipeline logic testable on CPU hosts (tests inject a numpy
    parity_fn and still exercise tiling/ordering/write paths).
    """
    if (parity_fn is None) != (fetch_fn is None):
        raise ValueError("parity_fn and fetch_fn must be injected together")
    if parity_fn is None:
        parity_fn, fetch_fn = _tpu_encode_fns()
    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES

    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    from seaweedfs_tpu.ec.ec_files import iter_ec_tiles, read_dat_tile, to_ext

    outputs = [open(base_file_name + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=1)
    write_q: queue.Queue = queue.Queue(maxsize=_INFLIGHT)
    # per-stage busy seconds (queue waits excluded): read | dispatch |
    # fetch (codec drain) | write — how e2e numbers stay attributable
    busy = {"read_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0, "write_s": 0.0}
    wall0 = time.perf_counter()

    def reader():
        with open(dat_path, "rb") as dat:
            for row_off, block, batch_off, step in iter_ec_tiles(
                dat_size, tile_bytes, large_block_size, small_block_size
            ):
                t0 = time.perf_counter()
                tile = read_dat_tile(dat, dat_size, row_off, block, batch_off, step)
                busy["read_s"] += time.perf_counter() - t0
                if not _q_put(read_q, tile, pipe.stop):
                    return
        _q_put(read_q, _EOF, pipe.stop)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            tile, handle = item
            t0 = time.perf_counter()
            parity = fetch_fn(handle)
            t1 = time.perf_counter()
            # buffer-protocol writes: a tobytes() copy per row doubled
            # the writer's memory traffic
            for i in range(DATA_SHARDS):
                outputs[i].write(tile[i])
            for i in range(PARITY_SHARDS):
                outputs[DATA_SHARDS + i].write(np.ascontiguousarray(parity[i]))
            busy["fetch_s"] += t1 - t0
            busy["write_s"] += time.perf_counter() - t1

    pipe.spawn(reader)
    pipe.spawn(writer)
    ok = False
    try:
        while True:
            tile = _q_get(read_q, pipe.stop)
            if tile is _EOF or tile is _STOPPED:
                break
            t0 = time.perf_counter()
            handle = parity_fn(tile)
            busy["dispatch_s"] += time.perf_counter() - t0
            if not _q_put(write_q, (tile, handle), pipe.stop):
                break
        _q_put(write_q, _EOF, pipe.stop)
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            try:
                for f in outputs:
                    f.close()
            finally:
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(stats, busy, wall0)


def stream_rebuild_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    rebuild_fn: Callable[[tuple[int, ...], tuple[int, ...], np.ndarray], "object"]
    | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
) -> list[int]:
    """Pipelined shard rebuild, byte-identical to rebuild_ec_files.

    rebuild_fn(survivors, targets, [10, step] u8) dispatches
    reconstruction of `targets` from the survivor tile and returns a
    handle; fetch_fn blocks it into [len(targets), step] u8."""
    if (rebuild_fn is None) != (fetch_fn is None):
        raise ValueError("rebuild_fn and fetch_fn must be injected together")
    if rebuild_fn is None:
        rebuild_fn, fetch_fn = _tpu_rebuild_fns()
    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES

    from seaweedfs_tpu.ec.ec_files import shard_presence, to_ext

    present, missing = shard_presence(base_file_name)
    if not missing:
        return []
    if sum(present) < DATA_SHARDS:
        raise ValueError(
            f"too few shard files to rebuild: {sum(present)} of {DATA_SHARDS}"
        )
    survivors = tuple(i for i, p in enumerate(present) if p)[:DATA_SHARDS]
    targets = tuple(missing)

    inputs = {i: open(base_file_name + to_ext(i), "rb") for i in survivors}
    outputs = {i: open(base_file_name + to_ext(i), "wb") for i in missing}
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=1)
    write_q: queue.Queue = queue.Queue(maxsize=_INFLIGHT)
    busy = {"read_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0, "write_s": 0.0}
    wall0 = time.perf_counter()

    def reader():
        shard_size = os.path.getsize(base_file_name + to_ext(survivors[0]))
        offset = 0
        while offset < shard_size:
            t0 = time.perf_counter()
            step = min(tile_bytes, shard_size - offset)
            tile = np.empty((DATA_SHARDS, step), dtype=np.uint8)
            for j, i in enumerate(survivors):
                # preadv straight into the tile row: os.pread would
                # allocate a bytes object and pay a second memcpy
                got = os.preadv(inputs[i].fileno(), [tile[j]], offset)
                if got != step:
                    raise ValueError(
                        f"ec shard {i} truncated: expected {step} at {offset}"
                    )
            busy["read_s"] += time.perf_counter() - t0
            if not _q_put(read_q, tile, pipe.stop):
                return
            offset += step
        _q_put(read_q, _EOF, pipe.stop)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            t0 = time.perf_counter()
            rebuilt = fetch_fn(item)
            t1 = time.perf_counter()
            for j, i in enumerate(targets):
                outputs[i].write(np.ascontiguousarray(rebuilt[j]))
            busy["fetch_s"] += t1 - t0
            busy["write_s"] += time.perf_counter() - t1

    pipe.spawn(reader)
    pipe.spawn(writer)
    ok = False
    try:
        while True:
            tile = _q_get(read_q, pipe.stop)
            if tile is _EOF or tile is _STOPPED:
                break
            t0 = time.perf_counter()
            handle = rebuild_fn(survivors, targets, tile)
            busy["dispatch_s"] += time.perf_counter() - t0
            if not _q_put(write_q, handle, pipe.stop):
                break
        _q_put(write_q, _EOF, pipe.stop)
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            try:
                for f in outputs.values():
                    f.close()
            finally:
                # an ENOSPC surfacing in a buffered close must not skip
                # the stats nor leak the 10 survivor read fds
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(stats, busy, wall0)
                for f in inputs.values():
                    f.close()
    return missing


def _finish_stats(stats: dict, busy: dict, wall0: float) -> None:
    """Per-stage busy seconds + wall and the unattributed remainder.
    The PIPELINE stages (read/dispatch/fetch/write) run in three
    threads, so their Σ can legitimately exceed wall (overlap) — the
    wall they explain is their max. flush_s is different: it is the
    SERIAL post-pipeline close (kernel writeback) appended to the
    wall, so it subtracts separately. loop_s = wall − flush − max
    pipeline stage: the honest "pipeline was idle / Python glue"
    residue for a bench line to carry."""
    wall = time.perf_counter() - wall0
    flush = busy.get("flush_s", 0.0)
    pipeline_max = max(
        (v for k, v in busy.items() if k != "flush_s"), default=0.0
    )
    stats.update({k: round(v, 4) for k, v in busy.items()})
    stats["wall_s"] = round(wall, 4)
    stats["loop_s"] = round(wall - flush - pipeline_max, 4)


# --- default TPU kernel stages ---------------------------------------------


def _swar_ok(step: int) -> bool:
    from seaweedfs_tpu.ec.codec_tpu import _SWAR_MIN_BYTES, _on_tpu

    return step % 1024 == 0 and step >= _SWAR_MIN_BYTES and _on_tpu()


def _fetch(handle) -> np.ndarray:
    """Block a dispatched kernel handle into a host uint8 array."""
    import jax

    out, swar = handle
    host = np.asarray(jax.device_get(out))
    return host.view(np.uint8) if swar else host


def _tpu_encode_fns():
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)

    def parity_fn(tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        if swar:
            u32 = jnp.asarray(tile.view(np.uint32))  # async H2D
            out = kern.encode_u32(u32)  # async dispatch
        else:
            out = kern.encode(jnp.asarray(tile))
        return out, swar

    return parity_fn, _fetch


def _tpu_rebuild_fns():
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)

    def rebuild_fn(survivors, targets, tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        if swar:
            u32 = jnp.asarray(tile.view(np.uint32))
            out = kern.reconstruct_u32(survivors, targets, u32)
        else:
            out = kern.reconstruct(survivors, targets, jnp.asarray(tile))
        return out, swar

    return rebuild_fn, _fetch
