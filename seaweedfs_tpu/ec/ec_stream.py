"""Flush-free, pool-parallel host↔HBM streaming drivers for EC
encode/rebuild.

The classic drivers in ec_files.py are synchronous: read a batch,
round-trip it through the codec, write, repeat — every stage waits for
every other. These drivers pipeline the stages the TPU-first way
(SURVEY §7 step 2 "streaming driver double-buffers tiles host↔HBM"),
matching the *output bytes* of ec_files.py exactly while overlapping:

  disk reads (tiles t+1..)  ‖  H2D + SWAR kernel (tile t)  ‖  parity
  D2H + shard writes (tiles t-1..)

Round 5 measured the previous single-reader/single-writer version
losing 47% of encode wall to a SERIAL buffered-file flush at close and
the rebuild reader serializing ten preadv calls on one thread. This
version removes both bottlenecks:

  * shard files are opened as RAW fds, preallocated to their exact
    final size (posix_fallocate, ftruncate fallback), and written with
    positioned os.pwritev at each tile's precomputed output offset —
    no userspace buffering accumulates, so close() is free and
    `flush_s` measures only the os.close loop;
  * a READER POOL claims tiles from a shared index and fills a bounded
    queue (each thread owns its fds: positioned preadv, no seek
    state), so the ten survivor reads of a rebuild tile — or tiles of
    the encode .dat — land in parallel instead of one serial loop;
  * a WRITER POOL drains dispatched tiles: each worker blocks on its
    tile's parity fetch and lands all rows with pwritev. Positioned
    writes make tile COMPLETION ORDER irrelevant to the bytes — every
    byte offset is written exactly once — so the pool needs no
    re-sequencing to stay byte-identical to the synchronous drivers;
  * the in-flight window is 3 dispatched-but-unfetched tiles deep (on
    TPU hosts the H2D stage donates its staging buffer to XLA, see
    _tpu_encode_fns), so H2D, kernel, and D2H genuinely
    triple-overlap.

Only the [4, N] parity ever crosses device→host on encode — the ten
data-shard files are byte copies of the blocks read from the .dat,
written straight from the host tile.

The rebuild driver additionally accepts REMOTE survivor readers
(`remote_readers`: shard id → fetch(offset, size) callables), which is
how the volume server's VolumeEcShardsRebuild verb overlaps rack-wide
shard gathering with reconstruction instead of copying every survivor
to the rebuilder before decoding byte one.

Role match: the 256 KB-batch loops at reference
weed/storage/erasure_coding/ec_encoder.go:188-225 (encodeDatFile) and
:227-281 (rebuildEcFiles), rebuilt as a pooled pipelined driver.
"""

from __future__ import annotations

import errno
import os
import sys
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

from seaweedfs_tpu import trace
from seaweedfs_tpu.ec import locate
from seaweedfs_tpu.stats.metrics import (
    EC_REPAIR_BYTES_READ,
    EC_REPAIR_BYTES_WRITTEN,
)

DATA_SHARDS = locate.DATA_SHARDS
PARITY_SHARDS = locate.PARITY_SHARDS
TOTAL_SHARDS = locate.TOTAL_SHARDS
LARGE_BLOCK_SIZE = locate.LARGE_BLOCK_SIZE
SMALL_BLOCK_SIZE = locate.SMALL_BLOCK_SIZE

# Per-shard bytes per pipelined tile. 4 MiB x 10 shards = 40 MiB of
# host buffer per in-flight stage (on the encode path, up to 4
# small-tier rows fold into one super-tile — see stream_write's
# reader). Swept on the 2-core rig: bigger tiles amortize syscalls but
# starve the pipeline of overlap on small volumes; 4 MiB won on the
# disk-backed scratch, 1-2 MiB on tmpfs, 8 MiB lost on both.
DEFAULT_TILE_BYTES = 4 * 1024 * 1024
# Dispatched-but-unfetched tiles queued toward the writer pool. Live
# host-tile bound: _INFLIGHT queued + one per writer thread (being
# fetched/written) + reader_threads + 2 (read queue + the
# dispatcher's hands) — 10 tiles at the defaults.
_INFLIGHT = 3
# Pool widths: the threads spend their time in GIL-released syscalls
# (preadv/pwritev), GIL-released C codec calls, or blocking device
# fetches, so a few of them keep the disks busy even on small hosts —
# but every extra thread costs GIL churn, and a 2-core-host sweep
# measured w=3/r=2 beating both w=2 and w=8 (BENCH r06 notes).
DEFAULT_WRITER_THREADS = min(8, max(3, (os.cpu_count() or 2) + 1))
DEFAULT_READER_THREADS = min(4, max(2, (os.cpu_count() or 2) // 2))

_EOF = object()  # end-of-stream marker flowing through the queues
_STOPPED = object()  # returned by _q_get when the pipeline aborted

_Q_TICK = 0.2  # seconds between stop-flag checks while blocked


def _q_put(q: queue.Queue, item, stop: threading.Event) -> bool:
    """put() that gives up when the pipeline aborts (a dead consumer
    must not leave the producer blocked forever)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=_Q_TICK)
            return True
        except queue.Full:
            continue
    return False


def _q_get(q: queue.Queue, stop: threading.Event):
    while not stop.is_set():
        try:
            return q.get(timeout=_Q_TICK)
        except queue.Empty:
            continue
    return _STOPPED


class _Pipeline:
    """Reader/writer pool threads around the caller's dispatch loop,
    with first-error propagation and deadlock-free shutdown."""

    def __init__(self):
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self._threads: list[threading.Thread] = []

    def spawn(self, fn) -> None:
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised on join
                self.errors.append(e)
                self.stop.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._threads.append(t)

    def finish(self, caller_error: bool = False) -> None:
        """Join the stage threads; re-raise the first stage error."""
        if caller_error:
            self.stop.set()
        for t in self._threads:
            t.join()
        if not caller_error and self.errors:
            raise self.errors[0]


# --- raw-fd IO primitives ---------------------------------------------------


def _preallocate(fd: int, size: int) -> None:
    """Reserve the file's exact final extent up front so ENOSPC fails
    before the pipeline spins up and close() has no deferred work.
    posix_fallocate allocates real blocks where the filesystem supports
    it; anything it can't do degrades to ftruncate (sparse extent —
    every byte is positioned-written exactly once anyway)."""
    if size <= 0:
        return
    try:
        os.posix_fallocate(fd, 0, size)
        return
    except OSError as e:
        if e.errno == errno.ENOSPC:
            raise
    os.ftruncate(fd, size)


def _pwrite_full(fd: int, buf, offset: int) -> None:
    """Positioned write of the whole buffer (pwritev can short-write on
    signals / rlimits; a silent short write would corrupt the shard)."""
    _pwritev_full(fd, [buf], offset)


def _pwritev_full(fd: int, bufs, offset: int) -> None:
    """Positioned gathered write of every buffer, restarting cleanly
    across short writes. One syscall lands a super-tile's whole run of
    per-row blocks for a shard (buffers need not be contiguous in
    memory — they ARE contiguous in the shard file)."""
    mvs = [memoryview(b).cast("B") for b in bufs]
    written = 0
    while mvs:
        w = os.pwritev(fd, mvs, offset + written)
        if w <= 0:
            raise OSError(errno.EIO, f"short pwritev at {offset + written}")
        written += w
        while mvs and w >= len(mvs[0]):
            w -= len(mvs[0])
            mvs.pop(0)
        if mvs and w:
            mvs[0] = mvs[0][w:]


def _pread_into(fd: int, view, offset: int) -> int:
    """Positioned read filling `view` (a writable uint8 buffer); stops
    early only at EOF. Returns bytes read."""
    mv = memoryview(view).cast("B")
    got = 0
    n = len(mv)
    while got < n:
        r = os.preadv(fd, [mv[got:]], offset + got)
        if r == 0:
            break
        got += r
    return got


def _charge(busy: dict, lock: threading.Lock, key: str, dt: float) -> None:
    """Accumulate per-stage busy seconds across pool threads (a stage
    total can legitimately exceed wall — it is thread-seconds)."""
    with lock:
        busy[key] += dt


# --- codec stage factories --------------------------------------------------


def local_encode_fns(rs) -> tuple[Callable, Callable]:
    """(parity_fn, fetch_fn) for a host ReedSolomon backend.

    Unlike the TPU pair — where parity_fn dispatches async device work
    — a host codec has no async engine, so parity_fn just hands the
    tile through and fetch_fn runs the actual matrix apply IN THE
    WRITER POOL. The native SIMD shim releases the GIL inside its C
    call, so W writer threads encode W tiles concurrently instead of
    serializing the codec on the dispatcher thread (measured: the
    single-thread native encode rate was the whole pipeline's cap)."""

    def fetch_fn(tile: np.ndarray):
        return rs._apply(rs.parity_rows, tile)

    return (lambda tile: tile), fetch_fn


def local_rebuild_fns(rs) -> tuple[Callable, Callable]:
    """(rebuild_fn, fetch_fn) over a host ReedSolomon backend, with the
    inverted-survivor decode rows cached on the codec (rs.decode_rows)
    and the decode itself deferred to the writer pool (see
    local_encode_fns)."""

    def rebuild_fn(survivors, targets, tile: np.ndarray):
        return (tuple(survivors), tuple(targets), tile)

    def fetch_fn(handle):
        survivors, targets, tile = handle
        return rs._apply(rs.decode_rows(survivors, targets), tile)

    return rebuild_fn, fetch_fn


# --- encode driver ----------------------------------------------------------


def stream_write_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    parity_fn: Callable[[np.ndarray], "object"] | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
    writer_threads: int | None = None,
    reader_threads: int | None = None,
    durable: bool = False,
) -> None:
    """Pipelined .dat → .ec00…13, byte-identical to write_ec_files.

    durable=True fsyncs every shard fd before returning — the ordering
    the generate verbs need so the .ecx publish that follows can imply
    "shard bytes are on disk" after a crash (weedcrash finding,
    docs/ANALYSIS.md v3: the writer pool's pwritev stream is otherwise
    entirely page-cache-resident when the .ecx lands).

    parity_fn([10, step] u8 host tile) must *dispatch* the parity
    computation and return an opaque handle immediately; fetch_fn turns
    the handle into a [4, step] u8 numpy array (blocking; called
    concurrently from the writer pool, so both must be thread-safe).
    The defaults run the SWAR kernel on the attached TPU. The
    indirection keeps the pipeline logic testable on CPU hosts (tests
    inject a numpy parity_fn and still exercise tiling/offsets/write
    paths)."""
    if (parity_fn is None) != (fetch_fn is None):
        raise ValueError("parity_fn and fetch_fn must be injected together")
    if parity_fn is None:
        parity_fn, fetch_fn = _tpu_encode_fns()
    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS

    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    from seaweedfs_tpu.ec.ec_files import iter_ec_tiles, to_ext

    # tiles and their shard-file output offsets, precomputed: each tile
    # contributes exactly `width` bytes per shard in generation order,
    # so positioned writes land it wherever it finishes. Consecutive
    # FULL-ROW tiles (the whole small-block tier once tile_bytes ≥
    # small_block_size) merge into SUPER-TILES of up to tile_bytes per
    # shard: one contiguous .dat read, one codec call, and one pwritev
    # per shard then carry `rows` rows each — per-row 1 MiB granularity
    # drowned the pipeline in syscall + GIL round-trips.
    tiles: list[tuple[int, int, int, int, int]] = []  # (row_off, block, batch_off, step, rows)
    for row_off, block, batch_off, step in iter_ec_tiles(
        dat_size, tile_bytes, large_block_size, small_block_size
    ):
        if tiles and batch_off == 0 and step == block:
            p_off, p_block, p_batch, p_step, p_rows = tiles[-1]
            if (
                p_batch == 0
                and p_step == p_block == block
                and p_off + p_rows * block * DATA_SHARDS == row_off
                and (p_rows + 1) * block <= tile_bytes
            ):
                tiles[-1] = (p_off, p_block, 0, p_step, p_rows + 1)
                continue
        tiles.append((row_off, block, batch_off, step, 1))
    out_offs, shard_bytes = [], 0
    for _, _, _, step, rows in tiles:
        out_offs.append(shard_bytes)
        shard_bytes += step * rows

    out_fds: list[int] = []  # opened inside the try: no leak on ENOSPC
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=_INFLIGHT)
    # per-stage busy thread-seconds (queue waits excluded): read |
    # dispatch | fetch (codec drain) | write — how e2e numbers stay
    # attributable
    busy = {"read_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0, "write_s": 0.0}
    busy_lock = threading.Lock()
    wall0 = time.perf_counter()
    # tracing plane: the encode is one span whose stages are the pool
    # busy totals; entered manually because the body below already owns
    # the try/finally structure
    _sp = trace.span("ec_stream.encode", nbytes=dat_size)
    _sp.__enter__()

    idx_lock = threading.Lock()
    idx_iter = iter(range(len(tiles)))

    def reader():
        fd = os.open(dat_path, os.O_RDONLY)
        try:
            while True:
                with idx_lock:
                    k = next(idx_iter, None)
                if k is None:
                    return
                row_off, block, batch_off, step, rows = tiles[k]
                t0 = time.perf_counter()
                # one flat [rows, 10, step] buffer per tile, preadv
                # straight into it (no bytes objects, no shared seek
                # position across the pool), zero-padded past EOF like
                # read_dat_tile — and only spans the .dat does not
                # cover pay the memset. NO reshuffling into shard
                # order: the codec consumes contiguous per-row [10,
                # step] views and the writer gather-writes each shard's
                # run of blocks with one iovec pwritev, so the bytes
                # are copied exactly once between disk reads and
                # writes.
                flat = np.empty(rows * DATA_SHARDS * step, dtype=np.uint8)
                if batch_off == 0 and step == block:
                    # full rows are CONTIGUOUS in the .dat: one read
                    # covers the whole super-tile
                    n = max(0, min(len(flat), dat_size - row_off))
                    if n < len(flat):
                        flat[n:] = 0
                    if n:
                        got = _pread_into(fd, flat[:n], row_off)
                        if got < n:  # truncated .dat: pad like classic
                            flat[got:n] = 0
                else:
                    # sub-block tile of the large tier: rows == 1,
                    # shard blocks are strided through the .dat
                    for i in range(DATA_SHARDS):
                        row = flat[i * step : (i + 1) * step]
                        off = row_off + i * block + batch_off
                        n = max(0, min(step, dat_size - off))
                        if n < step:
                            row[n:] = 0
                        if n:
                            got = _pread_into(fd, row[:n], off)
                            if got < n:
                                row[got:n] = 0
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (k, flat), pipe.stop):
                    return
        finally:
            os.close(fd)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            k, flat, handles = item
            _, _, _, step, rows = tiles[k]
            off = out_offs[k]
            t0 = time.perf_counter()
            parities = [fetch_fn(h) for h in handles]
            t1 = time.perf_counter()
            for i in range(DATA_SHARDS):
                _pwritev_full(
                    out_fds[i],
                    [
                        flat[
                            (r * DATA_SHARDS + i) * step : (r * DATA_SHARDS + i + 1)
                            * step
                        ]
                        for r in range(rows)
                    ],
                    off,
                )
            for p in range(PARITY_SHARDS):
                _pwritev_full(
                    out_fds[DATA_SHARDS + p],
                    [np.ascontiguousarray(parities[r][p]) for r in range(rows)],
                    off,
                )
            t2 = time.perf_counter()
            _charge(busy, busy_lock, "fetch_s", t1 - t0)
            _charge(busy, busy_lock, "write_s", t2 - t1)

    ok = False
    try:
        for i in range(TOTAL_SHARDS):
            out_fds.append(
                os.open(
                    base_file_name + to_ext(i),
                    os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                    0o644,
                )
            )
        for fd in out_fds:
            _preallocate(fd, shard_bytes)
        for _ in range(reader_threads):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(len(tiles)):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            k, flat = item
            _, _, _, step, rows = tiles[k]
            t0 = time.perf_counter()
            # one parity dispatch per row: each [10, step] view is
            # contiguous in the flat buffer, so the injected stage
            # contract (and the TPU H2D) sees an ordinary tile
            handles = [
                parity_fn(
                    flat[
                        r * DATA_SHARDS * step : (r + 1) * DATA_SHARDS * step
                    ].reshape(DATA_SHARDS, step)
                )
                for r in range(rows)
            ]
            _charge(busy, busy_lock, "dispatch_s", time.perf_counter() - t0)
            if not _q_put(write_q, (k, flat, handles), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fd in out_fds:
                    try:
                        if durable and ok and not pipe.errors:
                            # a failed durability fsync must FAIL the
                            # encode (swallowing it would ack bytes that
                            # never reached disk — the exact state the
                            # weedcrash ec-encode workload forbids), but
                            # only after every fd is closed
                            try:
                                os.fsync(fd)  # see the docstring contract
                            except OSError as e:
                                if fsync_err is None:
                                    fsync_err = e
                        os.close(fd)
                    except OSError:
                        pass
                if not ok or pipe.errors or fsync_err is not None:
                    # a partial shard set must not survive the abort:
                    # shard_presence treats ANY existing .ecNN as a
                    # valid shard, so full-size garbage files would
                    # read as a complete volume to a later rebuild
                    for i in range(TOTAL_SHARDS):
                        try:
                            os.remove(base_file_name + to_ext(i))
                        except OSError:
                            pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                # raw preallocated fds: nothing buffered remains, so
                # this measures only the close syscalls (the previous
                # driver lost 47% of wall right here)
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                _trace_stages(_sp, busy)
                # a stage error re-raised by pipe.finish() is live in
                # this finally; hand it to the span so a failed drive
                # is distinguishable from a clean one in /debug/traces
                _sp.__exit__(*sys.exc_info())


# --- rebuild driver ---------------------------------------------------------


def stream_rebuild_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    rebuild_fn: Callable[[tuple[int, ...], tuple[int, ...], np.ndarray], "object"]
    | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
    remote_readers: dict[int, Callable[[int, int], bytes]] | None = None,
    writer_threads: int | None = None,
    reader_threads: int | None = None,
    session=None,
    durable: bool = False,
) -> list[int]:
    """Pipelined shard rebuild, byte-identical to rebuild_ec_files.

    rebuild_fn(survivors, targets, [10, step] u8) dispatches
    reconstruction of `targets` from the survivor tile and returns a
    handle; fetch_fn blocks it into [len(targets), step] u8 (called
    from the writer pool — both must be thread-safe).

    remote_readers maps shard id → fetch(offset, size) -> bytes for
    survivors that live on OTHER nodes: the reader pool pulls their
    tiles over the wire in parallel with local preadv and the decode,
    and shards readable remotely are treated as present (not rebuilt).
    At least one survivor must be local — its file size fixes the tile
    walk.

    `session` (an ec.repair_session.RebuildSession) is the repair-
    bandwidth-frugal hookup: tiles degraded serving already decoded are
    consumed as donations, so the reader gathers survivors only for the
    GAPS — range-aligned sub-shard reads instead of the naive whole-
    range k-gather — and the reader yields to in-flight degraded
    gathers between tiles (serving never starves behind repair). Every
    survivor byte gathered is counted local-vs-remote on
    weed_ec_repair_bytes_read_total, every rebuilt byte written on
    weed_ec_repair_bytes_written_total.

    `durable=True` fsyncs the rebuilt shard files before returning
    (the weedcrash contract for the generate/rebuild verbs: an acked
    shard set survives a crash — docs/ANALYSIS.md v3)."""
    if (rebuild_fn is None) != (fetch_fn is None):
        raise ValueError("rebuild_fn and fetch_fn must be injected together")
    if rebuild_fn is None:
        rebuild_fn, fetch_fn = _tpu_rebuild_fns()
    # rebuild tiles read one span from each of 10 FILES (no contiguous
    # row to coalesce, unlike encode), so bigger tiles amortize better
    tile_bytes = tile_bytes or 2 * DEFAULT_TILE_BYTES
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    remote_readers = dict(remote_readers or {})

    from seaweedfs_tpu.ec.ec_files import shard_presence, to_ext

    present, local_missing = shard_presence(base_file_name)
    local_ids = [i for i, p in enumerate(present) if p]
    # a shard readable remotely exists in the cluster: it can serve as
    # a survivor but must not be rebuilt here
    targets = tuple(i for i in local_missing if i not in remote_readers)
    if not targets:
        return []
    remote_ids = [i for i in remote_readers if not present[i]]
    if len(local_ids) + len(remote_ids) < DATA_SHARDS:
        raise ValueError(
            "too few shard files to rebuild: "
            f"{len(local_ids) + len(remote_ids)} of {DATA_SHARDS}"
        )
    if not local_ids:
        raise ValueError(
            "rebuild needs at least one local survivor (its size fixes "
            "the shard length)"
        )
    # prefer local survivors (free reads), top up from remote holders;
    # the decode matrix keeps the chosen set in ascending order — any
    # 10-of-14 subset reconstructs identical bytes
    survivors = tuple(
        sorted((local_ids + sorted(remote_ids))[:DATA_SHARDS])
    )
    shard_size = os.path.getsize(base_file_name + to_ext(local_ids[0]))

    out_fds: dict[int, int] = {}  # opened inside the try: no leak on ENOSPC
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=_INFLIGHT)
    busy = {"read_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0, "write_s": 0.0}
    busy_lock = threading.Lock()
    wall0 = time.perf_counter()
    # tracing plane: rebuild span (inherits the scrub/repair plane tag
    # when the caller's context carries one — cross-plane interference
    # is then directly measurable on /debug/traces)
    _sp = trace.span(
        "ec_stream.rebuild", nbytes=shard_size * max(1, len(targets))
    )
    _sp.__enter__()

    offsets = list(range(0, shard_size, tile_bytes))
    idx_lock = threading.Lock()
    idx_iter = iter(offsets)

    n_remote = sum(1 for i in survivors if not present[i])
    read_local = EC_REPAIR_BYTES_READ.labels("local")
    read_remote = EC_REPAIR_BYTES_READ.labels("remote")

    def reader():
        fds = {
            i: os.open(base_file_name + to_ext(i), os.O_RDONLY)
            for i in survivors
            if present[i]
        }
        # remote survivor fetches fan out per tile: serialized, a
        # tile's latency would be n_remote × RTT and a single slow
        # holder would stall the whole tile walk
        fetch_pool = (
            ThreadPoolExecutor(max_workers=min(n_remote, DATA_SHARDS))
            if n_remote > 1
            else None
        )

        def gather(g_off: int, g_len: int) -> np.ndarray:
            """One [k, g_len] survivor read at g_off — the only place
            rebuild bytes cross a disk or the network, so the repair
            accounting lives here."""
            tile = np.empty((DATA_SHARDS, g_len), dtype=np.uint8)
            futures = {}
            if fetch_pool is not None:
                futures = {
                    j: fetch_pool.submit(remote_readers[i], g_off, g_len)
                    for j, i in enumerate(survivors)
                    if i not in fds
                }
            for j, i in enumerate(survivors):
                if i in fds:
                    got = _pread_into(fds[i], tile[j], g_off)
                    read_local.inc(got)
                else:
                    fut = futures.get(j)
                    raw = (
                        fut.result()
                        if fut is not None
                        else remote_readers[i](g_off, g_len)
                    )
                    got = len(raw)
                    read_remote.inc(got)
                    if got == g_len:
                        tile[j] = np.frombuffer(raw, dtype=np.uint8)
                if got != g_len:
                    raise ValueError(
                        f"ec shard {i} truncated: expected {g_len} at "
                        f"{g_off}"
                    )
            return tile

        try:
            while True:
                with idx_lock:
                    offset = next(idx_iter, None)
                if offset is None:
                    return
                if session is not None:
                    # serve-first arbitration: degraded GET gathers in
                    # flight own the disks/links; repair waits (bounded)
                    session.yield_to_serving()
                t0 = time.perf_counter()
                step = min(tile_bytes, shard_size - offset)
                if session is not None:
                    covered, gaps = session.consume(offset, step)
                else:
                    covered, gaps = [], [(offset, step)]
                # parts: ("don", off, {target: bytes}) ride through as
                # bytes; ("raw", off, [k, n] tile) get decoded. Only the
                # gaps pay survivor reads — donated ranges moved zero
                # new bytes (arXiv:2205.11015's partial-repair shape)
                parts: list = [
                    ("don", d_off, per_t) for d_off, per_t in covered
                ]
                for g_off, g_len in gaps:
                    parts.append(("raw", g_off, gather(g_off, g_len)))
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (offset, parts), pipe.stop):
                    return
        finally:
            if fetch_pool is not None:
                # wait for in-flight remote fetches: the caller closes
                # the reader channels right after the driver returns,
                # and an RPC still running on a pool thread would see
                # its channel yanked (and leak the thread past return)
                fetch_pool.shutdown(wait=True, cancel_futures=True)
            for fd in fds.values():
                os.close(fd)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            _offset, parts = item
            t0 = time.perf_counter()
            fetched = [
                (kind, off, fetch_fn(payload) if kind == "h" else payload)
                for kind, off, payload in parts
            ]
            t1 = time.perf_counter()
            for kind, off, payload in fetched:
                if kind == "don":
                    for i in targets:
                        _pwrite_full(out_fds[i], payload[i], off)
                        EC_REPAIR_BYTES_WRITTEN.inc(len(payload[i]))
                else:
                    for j, i in enumerate(targets):
                        row = np.ascontiguousarray(payload[j])
                        _pwrite_full(out_fds[i], row, off)
                        EC_REPAIR_BYTES_WRITTEN.inc(len(row))
            t2 = time.perf_counter()
            _charge(busy, busy_lock, "fetch_s", t1 - t0)
            _charge(busy, busy_lock, "write_s", t2 - t1)

    ok = False
    try:
        for i in targets:
            out_fds[i] = os.open(
                base_file_name + to_ext(i),
                os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                0o644,
            )
        for fd in out_fds.values():
            _preallocate(fd, shard_size)
        for _ in range(reader_threads):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(len(offsets)):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            offset, parts = item
            t0 = time.perf_counter()
            parts = [
                (
                    ("h", off, rebuild_fn(survivors, targets, payload))
                    if kind == "raw"
                    else (kind, off, payload)
                )
                for kind, off, payload in parts
            ]
            _charge(busy, busy_lock, "dispatch_s", time.perf_counter() - t0)
            if not _q_put(write_q, (offset, parts), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fd in out_fds.values():
                    try:
                        if durable and ok and not pipe.errors:
                            # crash contract (weedcrash, docs/ANALYSIS.md
                            # v3): a rebuild acked to its caller must
                            # survive power loss — pin the shard bytes
                            # before the fds close and the ack leaves;
                            # a FAILED fsync fails the rebuild (below)
                            # rather than acking page-cache-only bytes
                            try:
                                os.fsync(fd)
                            except OSError as e:
                                if fsync_err is None:
                                    fsync_err = e
                        os.close(fd)
                    except OSError:
                        pass
                if not ok or pipe.errors or fsync_err is not None:
                    # half-written targets must not survive: a later
                    # shard_presence would count the garbage files as
                    # valid shards and silently skip rebuilding them
                    # (e.g. ec.rebuild's full-copy fallback retry)
                    for i in targets:
                        try:
                            os.remove(base_file_name + to_ext(i))
                        except OSError:
                            pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                # an ENOSPC surfacing mid-stream must not skip the
                # stats nor leak any fd (the reader pool closes its own
                # survivor fds in its thread's finally)
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    if session is not None:
                        stats["donated_bytes"] = session.donated_bytes
                        stats["used_donated_bytes"] = (
                            session.used_donated_bytes
                        )
                        stats["serve_yields"] = session.yields
                _trace_stages(_sp, busy)
                if session is not None and _sp:
                    _sp.annotate("donated_bytes", session.used_donated_bytes)
                    _sp.annotate("serve_yields", session.yields)
                # a stage error re-raised by pipe.finish() is live in
                # this finally; hand it to the span so a failed drive
                # is distinguishable from a clean one in /debug/traces
                _sp.__exit__(*sys.exc_info())
    return list(targets)


def _trace_stages(sp, busy: dict) -> None:
    """Fold the driver's per-stage busy thread-seconds onto its span as
    the three pipeline stages an operator reasons about: reader-pool
    (disk/remote reads), compute (codec dispatch + drain), writer-pool
    (shard pwritev)."""
    sp.add_stages(
        {
            "reader-pool": busy.get("read_s", 0.0),
            "compute": busy.get("dispatch_s", 0.0) + busy.get("fetch_s", 0.0),
            "writer-pool": busy.get("write_s", 0.0),
        }
    )


def _finish_stats(
    stats: dict,
    busy: dict,
    wall0: float,
    reader_threads: int = 1,
    writer_threads: int = 1,
) -> None:
    """Per-stage busy thread-seconds + wall and the unattributed
    remainder. The PIPELINE stages (read/dispatch/fetch/write) run in
    thread POOLS, so a stage's Σ can exceed wall (overlap across
    threads) — the wall a stage explains is its total divided by its
    pool width. flush_s is different: it is the SERIAL post-pipeline
    close of the raw fds appended to the wall (≈0 now that nothing is
    buffered), so it subtracts separately. loop_s = wall − flush − max
    per-thread stage share: the honest "pipeline was idle / Python
    glue" residue for a bench line to carry (clamped at 0 — pool
    accounting is approximate)."""
    wall = time.perf_counter() - wall0
    flush = busy.get("flush_s", 0.0)
    widths = {
        "read_s": reader_threads,
        "fetch_s": writer_threads,
        "write_s": writer_threads,
    }
    pipeline_max = max(
        (
            v / widths.get(k, 1)
            for k, v in busy.items()
            if k != "flush_s"
        ),
        default=0.0,
    )
    stats.update({k: round(v, 4) for k, v in busy.items()})
    stats["wall_s"] = round(wall, 4)
    stats["loop_s"] = round(max(0.0, wall - flush - pipeline_max), 4)
    stats["reader_threads"] = reader_threads
    stats["writer_threads"] = writer_threads


# --- default TPU kernel stages ---------------------------------------------


def _swar_ok(step: int) -> bool:
    from seaweedfs_tpu.ec.codec_tpu import _SWAR_MIN_BYTES, _on_tpu

    return step % 1024 == 0 and step >= _SWAR_MIN_BYTES and _on_tpu()


def _fetch(handle) -> np.ndarray:
    """Block a dispatched kernel handle into a host uint8 array."""
    import jax

    out, swar = handle
    host = np.asarray(jax.device_get(out))
    return host.view(np.uint8) if swar else host


def _tpu_encode_fns():
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)
    # donate the H2D staging buffer: the [10, n32] tile is dead the
    # moment the kernel has read it, and with 3 tiles in flight XLA
    # recycling the donated extent keeps the deepened window from
    # growing HBM residency per tile
    encode_u32_don = jax.jit(
        lambda u32: kern.encode_u32(u32), donate_argnums=0
    )

    def parity_fn(tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        if swar:
            u32 = jnp.asarray(tile.view(np.uint32))  # async H2D
            out = encode_u32_don(u32)  # async dispatch
        else:
            out = kern.encode(jnp.asarray(tile))
        return out, swar

    return parity_fn, _fetch


def _tpu_rebuild_fns():
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)
    recon_don = jax.jit(
        lambda s, t, u32: kern.reconstruct_u32(s, t, u32),
        static_argnums=(0, 1),
        donate_argnums=2,
    )

    def rebuild_fn(survivors, targets, tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        if swar:
            u32 = jnp.asarray(tile.view(np.uint32))
            out = recon_don(tuple(survivors), tuple(targets), u32)
        else:
            out = kern.reconstruct(survivors, targets, jnp.asarray(tile))
        return out, swar

    return rebuild_fn, _fetch
