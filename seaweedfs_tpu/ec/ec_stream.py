"""Flush-free, pool-parallel host↔HBM streaming drivers for EC
encode/rebuild.

The classic drivers in ec_files.py are synchronous: read a batch,
round-trip it through the codec, write, repeat — every stage waits for
every other. These drivers pipeline the stages the TPU-first way
(SURVEY §7 step 2 "streaming driver double-buffers tiles host↔HBM"),
matching the *output bytes* of ec_files.py exactly while overlapping:

  disk reads (tiles t+1..)  ‖  H2D + SWAR kernel (tile t)  ‖  parity
  D2H + shard writes (tiles t-1..)

Round 5 measured the previous single-reader/single-writer version
losing 47% of encode wall to a SERIAL buffered-file flush at close and
the rebuild reader serializing ten preadv calls on one thread. This
version removes both bottlenecks:

  * shard files are opened as RAW fds, preallocated to their exact
    final size (posix_fallocate, ftruncate fallback), and written with
    positioned os.pwritev at each tile's precomputed output offset —
    no userspace buffering accumulates, so close() is free and
    `flush_s` measures only the os.close loop;
  * a READER POOL claims tiles from a shared index and fills a bounded
    queue (each thread owns its fds: positioned preadv, no seek
    state), so the ten survivor reads of a rebuild tile — or tiles of
    the encode .dat — land in parallel instead of one serial loop;
  * a WRITER POOL drains dispatched tiles: each worker blocks on its
    tile's parity fetch and lands all rows with pwritev. Positioned
    writes make tile COMPLETION ORDER irrelevant to the bytes — every
    byte offset is written exactly once — so the pool needs no
    re-sequencing to stay byte-identical to the synchronous drivers;
  * the in-flight window is 3 dispatched-but-unfetched tiles deep (on
    TPU hosts the H2D stage donates its staging buffer to XLA, see
    _tpu_encode_fns), so H2D, kernel, and D2H genuinely
    triple-overlap.

Only the [4, N] parity ever crosses device→host on encode — the ten
data-shard files are byte copies of the blocks read from the .dat,
written straight from the host tile.

The rebuild driver additionally accepts REMOTE survivor readers
(`remote_readers`: shard id → fetch(offset, size) callables), which is
how the volume server's VolumeEcShardsRebuild verb overlaps rack-wide
shard gathering with reconstruction instead of copying every survivor
to the rebuilder before decoding byte one.

Role match: the 256 KB-batch loops at reference
weed/storage/erasure_coding/ec_encoder.go:188-225 (encodeDatFile) and
:227-281 (rebuildEcFiles), rebuilt as a pooled pipelined driver.
"""

from __future__ import annotations

import errno
import os
import sys
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

from seaweedfs_tpu import trace
from seaweedfs_tpu.ec import locate
from seaweedfs_tpu.stats.metrics import (
    EC_REPAIR_BYTES_READ,
    EC_REPAIR_BYTES_WRITTEN,
)

DATA_SHARDS = locate.DATA_SHARDS
PARITY_SHARDS = locate.PARITY_SHARDS
TOTAL_SHARDS = locate.TOTAL_SHARDS
LARGE_BLOCK_SIZE = locate.LARGE_BLOCK_SIZE
SMALL_BLOCK_SIZE = locate.SMALL_BLOCK_SIZE

# Per-shard bytes per pipelined tile. 1 MiB x 10 shards = 10 MiB of
# host buffer per ring slot (on the encode path, small-tier rows fold
# into one super-tile — see stream_write's reader). Re-swept for the
# staging-ring driver on this rig (BENCH_r12): finer tiles give the
# reader pool more in-flight preads to overlap against compute, and
# 1 MiB beat 4 MiB 1.27 vs 0.81 GB/s on the disk-backed scratch while
# matching it on tmpfs (512 KiB was within noise of 1 MiB on both;
# 8 MiB lost everywhere). TPU dispatch amortization keeps the floor at
# 1 MiB — a [10, 1 MiB] tile is still 16x the SWAR minimum stream.
DEFAULT_TILE_BYTES = 1024 * 1024
# Dispatched-but-unfetched tiles queued toward the writer pool. Live
# host-tile bound: _INFLIGHT queued + one per writer thread (being
# fetched/written) + reader_threads + 2 (read queue + the
# dispatcher's hands) — 10 tiles at the defaults.
_INFLIGHT = 3


def pipeline_enabled() -> bool:
    """Kill switch for the whole device-resident pipeline plane:
    WEED_EC_PIPELINE=0 routes every encode/rebuild back through the
    serial classic drivers in ec_files.py wholesale (byte-identical;
    regression-tested) — the operator lever when a pipeline bug is
    suspected in production."""
    return os.environ.get("WEED_EC_PIPELINE", "1") != "0"


def pipeline_depth() -> int:
    """Dispatched-but-unfetched window (staging-ring dispatch depth):
    WEED_EC_PIPELINE_DEPTH, minimum 2 (double buffering — one tile on
    the device while the next stages), default 3."""
    try:
        d = int(os.environ.get("WEED_EC_PIPELINE_DEPTH", "0"))
    except ValueError:
        d = 0
    return max(2, d) if d > 0 else _INFLIGHT


def pipeline_batch_limit() -> int:
    """Max volumes per mesh dispatch round on the batched encode path
    (WEED_EC_PIPELINE_BATCH, 0 = whole batch in one program). Caps
    staging-ring memory: one ring slot is batch x 10 x tile bytes."""
    try:
        return max(0, int(os.environ.get("WEED_EC_PIPELINE_BATCH", "0")))
    except ValueError:
        return 0


class _StagingRing:
    """N preallocated host staging buffers cycled reader → dispatcher →
    writer → free. Replaces a fresh np.empty per tile: the pipeline's
    host memory is bounded at slots x slot_bytes for the whole run and
    the allocator drops out of the hot loop (page-faulting a new 40 MiB
    arena per tile showed up as unattributed wall in the loop_s
    residue). Slot count = dispatch depth + one in-hand buffer per pool
    thread, so no stage ever stalls waiting for memory another stage
    is legitimately using."""

    def __init__(self, slots: int, slot_bytes: int):
        self.slots = max(2, slots)
        self._bufs = [
            np.empty(slot_bytes, dtype=np.uint8) for _ in range(self.slots)
        ]
        self._free: queue.Queue = queue.Queue()
        for i in range(self.slots):
            self._free.put(i)

    def acquire(self, stop: threading.Event):
        """(slot id, flat uint8 buffer) or None when the pipeline
        aborted while waiting for a free slot."""
        i = _q_get(self._free, stop)
        if i is _STOPPED:
            return None
        return i, self._bufs[i]

    def release(self, slot_id: int) -> None:
        self._free.put(slot_id)


# Pool widths: the threads spend their time in GIL-released syscalls
# (preadv/pwritev), GIL-released C codec calls, or blocking device
# fetches, so a few of them keep the disks busy even on small hosts —
# but every extra thread costs GIL churn. Re-swept with the staging
# ring (BENCH_r12): the reader pool is the disk's IO queue, and a
# floor of 3 readers beat the old 2 even on a 1-CPU-quota host
# (1.24 vs 1.17 GB/s at the 1 MiB tile) because blocked preads cost
# no CPU; w=3 still beat w=2 and w=8.
DEFAULT_WRITER_THREADS = min(8, max(3, (os.cpu_count() or 2) + 1))
DEFAULT_READER_THREADS = min(6, max(3, (os.cpu_count() or 2) // 2))

_EOF = object()  # end-of-stream marker flowing through the queues
_STOPPED = object()  # returned by _q_get when the pipeline aborted

_Q_TICK = 0.2  # seconds between stop-flag checks while blocked


def _q_put(q: queue.Queue, item, stop: threading.Event) -> bool:
    """put() that gives up when the pipeline aborts (a dead consumer
    must not leave the producer blocked forever)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=_Q_TICK)
            return True
        except queue.Full:
            continue
    return False


def _q_get(q: queue.Queue, stop: threading.Event):
    while not stop.is_set():
        try:
            return q.get(timeout=_Q_TICK)
        except queue.Empty:
            continue
    return _STOPPED


class _Pipeline:
    """Reader/writer pool threads around the caller's dispatch loop,
    with first-error propagation and deadlock-free shutdown."""

    def __init__(self):
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self._threads: list[threading.Thread] = []

    def spawn(self, fn) -> None:
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised on join
                self.errors.append(e)
                self.stop.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._threads.append(t)

    def finish(self, caller_error: bool = False) -> None:
        """Join the stage threads; re-raise the first stage error."""
        if caller_error:
            self.stop.set()
        for t in self._threads:
            t.join()
        if not caller_error and self.errors:
            raise self.errors[0]


# --- raw-fd IO primitives ---------------------------------------------------


def _preallocate(fd: int, size: int) -> None:
    """Reserve the file's exact final extent up front so ENOSPC fails
    before the pipeline spins up and close() has no deferred work.
    posix_fallocate allocates real blocks where the filesystem supports
    it; anything it can't do degrades to ftruncate (sparse extent —
    every byte is positioned-written exactly once anyway)."""
    if size <= 0:
        return
    try:
        os.posix_fallocate(fd, 0, size)
        return
    except OSError as e:
        if e.errno == errno.ENOSPC:
            raise
    os.ftruncate(fd, size)


def _pwrite_full(fd: int, buf, offset: int) -> None:
    """Positioned write of the whole buffer (pwritev can short-write on
    signals / rlimits; a silent short write would corrupt the shard)."""
    _pwritev_full(fd, [buf], offset)


def _pwritev_full(fd: int, bufs, offset: int) -> None:
    """Positioned gathered write of every buffer, restarting cleanly
    across short writes. One syscall lands a super-tile's whole run of
    per-row blocks for a shard (buffers need not be contiguous in
    memory — they ARE contiguous in the shard file)."""
    mvs = [memoryview(b).cast("B") for b in bufs]
    written = 0
    while mvs:
        w = os.pwritev(fd, mvs, offset + written)
        if w <= 0:
            raise OSError(errno.EIO, f"short pwritev at {offset + written}")
        written += w
        while mvs and w >= len(mvs[0]):
            w -= len(mvs[0])
            mvs.pop(0)
        if mvs and w:
            mvs[0] = mvs[0][w:]


def _pread_into(fd: int, view, offset: int) -> int:
    """Positioned read filling `view` (a writable uint8 buffer); stops
    early only at EOF. Returns bytes read."""
    mv = memoryview(view).cast("B")
    got = 0
    n = len(mv)
    while got < n:
        r = os.preadv(fd, [mv[got:]], offset + got)
        if r == 0:
            break
        got += r
    return got


def _charge(busy: dict, lock: threading.Lock, key: str, dt: float) -> None:
    """Accumulate per-stage busy seconds across pool threads (a stage
    total can legitimately exceed wall — it is thread-seconds)."""
    with lock:
        busy[key] += dt


# --- codec stage factories --------------------------------------------------


def local_encode_fns(rs, want_crcs: bool = False) -> tuple[Callable, Callable]:
    """(parity_fn, fetch_fn) for a host ReedSolomon backend.

    Unlike the TPU pair — where parity_fn dispatches async device work
    — a host codec has no async engine, so parity_fn just hands the
    tile through and fetch_fn runs the actual matrix apply IN THE
    WRITER POOL. The native SIMD shim releases the GIL inside its C
    call, so W writer threads encode W tiles concurrently instead of
    serializing the codec on the dispatcher thread (measured: the
    single-thread native encode rate was the whole pipeline's cap).

    fetch_fn.charges = "compute_s": the matrix apply is HOST codec
    work, not a device drain — without the tag the stage breakdown
    would book the whole encode as writer-pool writeback time.

    want_crcs=True makes fetch_fn return (parity, [k+p] CRC-32C) pairs
    (codec.parity_with_crc) — the same fused-CRC stage contract the
    device pairs serve on-chip."""

    if want_crcs:

        def fetch_fn(tile: np.ndarray):
            return rs.parity_with_crc(tile)

    else:

        def fetch_fn(tile: np.ndarray):
            return rs._apply(rs.parity_rows, tile)

    fetch_fn.charges = "compute_s"
    return (lambda tile: tile), fetch_fn


def local_rebuild_fns(rs, want_crcs: bool = False) -> tuple[Callable, Callable]:
    """(rebuild_fn, fetch_fn) over a host ReedSolomon backend, with the
    inverted-survivor decode rows cached on the codec (rs.decode_rows)
    and the decode itself deferred to the writer pool (see
    local_encode_fns — including the compute_s charge tag and the
    want_crcs (rebuilt, crcs) contract)."""

    def rebuild_fn(survivors, targets, tile: np.ndarray):
        return (tuple(survivors), tuple(targets), tile)

    if want_crcs:
        from seaweedfs_tpu.util.crc import crc32c

        def fetch_fn(handle):
            survivors, targets, tile = handle
            rebuilt = rs._apply(rs.decode_rows(survivors, targets), tile)
            return rebuilt, [
                crc32c(np.ascontiguousarray(row).tobytes()) for row in rebuilt
            ]

    else:

        def fetch_fn(handle):
            survivors, targets, tile = handle
            return rs._apply(rs.decode_rows(survivors, targets), tile)

    fetch_fn.charges = "compute_s"
    return rebuild_fn, fetch_fn


# --- encode driver ----------------------------------------------------------


def stream_write_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    parity_fn: Callable[[np.ndarray], "object"] | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
    writer_threads: int | None = None,
    reader_threads: int | None = None,
    durable: bool = False,
    want_crcs: bool = False,
) -> None:
    """Pipelined .dat → .ec00…13, byte-identical to write_ec_files.

    durable=True fsyncs every shard fd before returning — the ordering
    the generate verbs need so the .ecx publish that follows can imply
    "shard bytes are on disk" after a crash (weedcrash finding,
    docs/ANALYSIS.md v3: the writer pool's pwritev stream is otherwise
    entirely page-cache-resident when the .ecx lands).

    parity_fn([10, step] u8 host tile) must *dispatch* the parity
    computation and return an opaque handle immediately; fetch_fn turns
    the handle into a [4, step] u8 numpy array — or a
    ([4, step] u8, [14] CRC-32C) pair when the stage computed fused
    shard CRCs (blocking; called concurrently from the writer pool, so
    both must be thread-safe). The defaults run the SWAR kernel on the
    attached TPU. The indirection keeps the pipeline logic testable on
    CPU hosts (tests inject a numpy parity_fn and still exercise
    tiling/offsets/write paths).

    want_crcs=True lands a 14-entry `shard_crcs` list in `stats`: the
    standard CRC-32C of every finished shard FILE, folded from the
    per-tile CRCs the stage pair returns (util/crc.crc32c_combine).
    Tiles whose stage pair declined the fused CRC (injected test fns,
    non-power-of-two tails) are checksummed host-side in the writer
    pool and charged to compute_s — the contract holds either way.

    Host staging buffers live in a _StagingRing of
    pipeline_depth() + writer_threads + 1 slots (WEED_EC_PIPELINE_DEPTH
    bounds the dispatched-but-unfetched window; the extras are the
    buffers pool threads legitimately hold while working), so pipeline
    memory is bounded and allocator churn stays out of the hot loop."""
    if (parity_fn is None) != (fetch_fn is None):
        raise ValueError("parity_fn and fetch_fn must be injected together")
    if parity_fn is None:
        parity_fn, fetch_fn = _tpu_encode_fns(want_crcs=want_crcs)
    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    depth = pipeline_depth()

    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    from seaweedfs_tpu.ec.ec_files import iter_ec_tiles, to_ext

    # tiles and their shard-file output offsets, precomputed: each tile
    # contributes exactly `width` bytes per shard in generation order,
    # so positioned writes land it wherever it finishes. Consecutive
    # FULL-ROW tiles (the whole small-block tier once tile_bytes ≥
    # small_block_size) merge into SUPER-TILES of up to tile_bytes per
    # shard: one contiguous .dat read, one codec call, and one pwritev
    # per shard then carry `rows` rows each — per-row 1 MiB granularity
    # drowned the pipeline in syscall + GIL round-trips.
    tiles: list[tuple[int, int, int, int, int]] = []  # (row_off, block, batch_off, step, rows)
    for row_off, block, batch_off, step in iter_ec_tiles(
        dat_size, tile_bytes, large_block_size, small_block_size
    ):
        if tiles and batch_off == 0 and step == block:
            p_off, p_block, p_batch, p_step, p_rows = tiles[-1]
            if (
                p_batch == 0
                and p_step == p_block == block
                and p_off + p_rows * block * DATA_SHARDS == row_off
                and (p_rows + 1) * block <= tile_bytes
            ):
                tiles[-1] = (p_off, p_block, 0, p_step, p_rows + 1)
                continue
        tiles.append((row_off, block, batch_off, step, 1))
    out_offs, shard_bytes = [], 0
    for _, _, _, step, rows in tiles:
        out_offs.append(shard_bytes)
        shard_bytes += step * rows

    out_fds: list[int] = []  # opened inside the try: no leak on ENOSPC
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=depth)
    # the staging ring: every in-flight tile lives in one of these
    # preallocated slots (flat [rows*10*step] prefixes of slot buffers)
    ring = _StagingRing(
        depth + writer_threads + 1, DATA_SHARDS * tile_bytes
    )
    # per-stage busy thread-seconds (queue waits excluded): read |
    # stage (host staging prep) | device (async dispatch) | writeback
    # (device drain / D2H) or compute (host codec) | write — how e2e
    # numbers stay attributable and reader/device/writer overlap is
    # provable per run
    busy = {
        "read_s": 0.0,
        "stage_s": 0.0,
        "device_s": 0.0,
        "writeback_s": 0.0,
        "compute_s": 0.0,
        "write_s": 0.0,
    }
    busy_lock = threading.Lock()
    fetch_bucket = getattr(fetch_fn, "charges", "writeback_s")
    # per-tile shard CRCs, filled by the writer pool (index writes are
    # GIL-atomic), folded into whole-file CRCs after the join
    tile_crcs: list = [None] * len(tiles)
    wall0 = time.perf_counter()
    # tracing plane: the encode is one span whose stages are the pool
    # busy totals; entered manually because the body below already owns
    # the try/finally structure
    _sp = trace.span("ec_stream.encode", nbytes=dat_size)
    _sp.__enter__()

    idx_lock = threading.Lock()
    idx_iter = iter(range(len(tiles)))

    def reader():
        fd = os.open(dat_path, os.O_RDONLY)
        try:
            while True:
                with idx_lock:
                    k = next(idx_iter, None)
                if k is None:
                    return
                row_off, block, batch_off, step, rows = tiles[k]
                got_slot = ring.acquire(pipe.stop)
                if got_slot is None:
                    return
                slot_id, buf = got_slot
                t0 = time.perf_counter()
                # one flat [rows, 10, step] ring-slot prefix per tile,
                # preadv straight into it (no bytes objects, no shared
                # seek position across the pool), zero-padded past EOF
                # like read_dat_tile — and only spans the .dat does not
                # cover pay the memset. NO reshuffling into shard
                # order: the codec consumes contiguous per-row [10,
                # step] views and the writer gather-writes each shard's
                # run of blocks with one iovec pwritev, so the bytes
                # are copied exactly once between disk reads and
                # writes.
                flat = buf[: rows * DATA_SHARDS * step]
                if batch_off == 0 and step == block:
                    # full rows are CONTIGUOUS in the .dat: one read
                    # covers the whole super-tile
                    n = max(0, min(len(flat), dat_size - row_off))
                    if n < len(flat):
                        flat[n:] = 0
                    if n:
                        got = _pread_into(fd, flat[:n], row_off)
                        if got < n:  # truncated .dat: pad like classic
                            flat[got:n] = 0
                else:
                    # sub-block tile of the large tier: rows == 1,
                    # shard blocks are strided through the .dat
                    for i in range(DATA_SHARDS):
                        row = flat[i * step : (i + 1) * step]
                        off = row_off + i * block + batch_off
                        n = max(0, min(step, dat_size - off))
                        if n < step:
                            row[n:] = 0
                        if n:
                            got = _pread_into(fd, row[:n], off)
                            if got < n:
                                row[got:n] = 0
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (k, slot_id, flat), pipe.stop):
                    ring.release(slot_id)
                    return
        finally:
            os.close(fd)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            k, slot_id, flat, handles = item
            _, _, _, step, rows = tiles[k]
            off = out_offs[k]
            t0 = time.perf_counter()
            parities, crc_rows = [], []
            for h in handles:
                got = fetch_fn(h)
                if isinstance(got, tuple):
                    parities.append(got[0])
                    crc_rows.append(got[1])
                else:
                    parities.append(got)
                    crc_rows.append(None)
            t1 = time.perf_counter()
            if want_crcs and any(c is None for c in crc_rows):
                # the stage declined the fused CRC for this tile
                # (injected pair / unsupported shape): table-CRC the
                # written bytes here, charged as host compute
                from seaweedfs_tpu.util.crc import crc32c

                for r, c in enumerate(crc_rows):
                    if c is not None:
                        continue
                    row0 = r * DATA_SHARDS * step
                    crc_rows[r] = [
                        crc32c(
                            flat[row0 + i * step : row0 + (i + 1) * step]
                            .tobytes()
                        )
                        for i in range(DATA_SHARDS)
                    ] + [
                        crc32c(np.ascontiguousarray(parities[r][p]).tobytes())
                        for p in range(PARITY_SHARDS)
                    ]
            t2 = time.perf_counter()
            for i in range(DATA_SHARDS):
                _pwritev_full(
                    out_fds[i],
                    [
                        flat[
                            (r * DATA_SHARDS + i) * step : (r * DATA_SHARDS + i + 1)
                            * step
                        ]
                        for r in range(rows)
                    ],
                    off,
                )
            for p in range(PARITY_SHARDS):
                _pwritev_full(
                    out_fds[DATA_SHARDS + p],
                    [np.ascontiguousarray(parities[r][p]) for r in range(rows)],
                    off,
                )
            t3 = time.perf_counter()
            if want_crcs:
                tile_crcs[k] = crc_rows
            ring.release(slot_id)
            _charge(busy, busy_lock, fetch_bucket, t1 - t0)
            _charge(busy, busy_lock, "compute_s", t2 - t1)
            _charge(busy, busy_lock, "write_s", t3 - t2)

    ok = False
    try:
        for i in range(TOTAL_SHARDS):
            out_fds.append(
                os.open(
                    base_file_name + to_ext(i),
                    os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                    0o644,
                )
            )
        for fd in out_fds:
            _preallocate(fd, shard_bytes)
        for _ in range(reader_threads):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(len(tiles)):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            k, slot_id, flat = item
            _, _, _, step, rows = tiles[k]
            t0 = time.perf_counter()
            # staging: each [10, step] view is contiguous in the ring
            # slot, so the injected stage contract (and the TPU H2D)
            # sees an ordinary tile
            views = [
                flat[
                    r * DATA_SHARDS * step : (r + 1) * DATA_SHARDS * step
                ].reshape(DATA_SHARDS, step)
                for r in range(rows)
            ]
            t1 = time.perf_counter()
            # one async parity dispatch per row
            handles = [parity_fn(v) for v in views]
            t2 = time.perf_counter()
            _charge(busy, busy_lock, "stage_s", t1 - t0)
            _charge(busy, busy_lock, "device_s", t2 - t1)
            if not _q_put(write_q, (k, slot_id, flat, handles), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fd in out_fds:
                    try:
                        if durable and ok and not pipe.errors:
                            # a failed durability fsync must FAIL the
                            # encode (swallowing it would ack bytes that
                            # never reached disk — the exact state the
                            # weedcrash ec-encode workload forbids), but
                            # only after every fd is closed
                            try:
                                os.fsync(fd)  # see the docstring contract
                            except OSError as e:
                                if fsync_err is None:
                                    fsync_err = e
                        os.close(fd)
                    except OSError:
                        pass
                if not ok or pipe.errors or fsync_err is not None:
                    # a partial shard set must not survive the abort:
                    # shard_presence treats ANY existing .ecNN as a
                    # valid shard, so full-size garbage files would
                    # read as a complete volume to a later rebuild
                    for i in range(TOTAL_SHARDS):
                        try:
                            os.remove(base_file_name + to_ext(i))
                        except OSError:
                            pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                # raw preallocated fds: nothing buffered remains, so
                # this measures only the close syscalls (the previous
                # driver lost 47% of wall right here)
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    stats["pipeline_depth"] = depth
                    stats["ring_slots"] = ring.slots
                    if (
                        want_crcs
                        and ok
                        and not pipe.errors
                        and fsync_err is None
                    ):
                        stats["shard_crcs"] = _fold_encode_crcs(
                            tiles, tile_crcs
                        )
                _trace_stages(_sp, busy)
                # a stage error re-raised by pipe.finish() is live in
                # this finally; hand it to the span so a failed drive
                # is distinguishable from a clean one in /debug/traces
                _sp.__exit__(*sys.exc_info())


def _fold_encode_crcs(tiles: list, tile_crcs: list) -> list[int]:
    """Whole-shard-file CRC-32C per shard from the per-tile row CRCs:
    fold in tile/row generation order with crc32c_combine (tiles land
    on disk in ANY order — positioned writes — but the fold is over
    the recorded CRCs, so completion order is irrelevant here too)."""
    from seaweedfs_tpu.util.crc import crc32c_combine

    crcs = [0] * TOTAL_SHARDS
    for k, (_, _, _, step, rows) in enumerate(tiles):
        per_rows = tile_crcs[k]
        for r in range(rows):
            row = per_rows[r]
            for i in range(TOTAL_SHARDS):
                crcs[i] = crc32c_combine(crcs[i], int(row[i]), step)
    return crcs


# --- rebuild driver ---------------------------------------------------------


def stream_rebuild_ec_files(
    base_file_name: str,
    tile_bytes: int | None = None,
    rebuild_fn: Callable[[tuple[int, ...], tuple[int, ...], np.ndarray], "object"]
    | None = None,
    fetch_fn: Callable[["object"], np.ndarray] | None = None,
    stats: dict | None = None,
    remote_readers: dict[int, Callable[[int, int], bytes]] | None = None,
    writer_threads: int | None = None,
    reader_threads: int | None = None,
    session=None,
    durable: bool = False,
    want_crcs: bool = False,
) -> list[int]:
    """Pipelined shard rebuild, byte-identical to rebuild_ec_files.

    rebuild_fn(survivors, targets, [10, step] u8) dispatches
    reconstruction of `targets` from the survivor tile and returns a
    handle; fetch_fn blocks it into [len(targets), step] u8 — or a
    ([len(targets), step] u8, [len(targets)] CRC-32C) pair when the
    stage fused the Castagnoli pass (called from the writer pool —
    both must be thread-safe).

    want_crcs=True lands `shard_crcs` in `stats`: a {shard id: CRC-32C
    of the whole rebuilt file} dict folded from per-range CRCs
    (device-fused where the stage supports the shape, host table CRC
    for donated ranges and odd tails, charged to compute_s).

    remote_readers maps shard id → fetch(offset, size) -> bytes for
    survivors that live on OTHER nodes: the reader pool pulls their
    tiles over the wire in parallel with local preadv and the decode,
    and shards readable remotely are treated as present (not rebuilt).
    At least one survivor must be local — its file size fixes the tile
    walk.

    `session` (an ec.repair_session.RebuildSession) is the repair-
    bandwidth-frugal hookup: tiles degraded serving already decoded are
    consumed as donations, so the reader gathers survivors only for the
    GAPS — range-aligned sub-shard reads instead of the naive whole-
    range k-gather — and the reader yields to in-flight degraded
    gathers between tiles (serving never starves behind repair). Every
    survivor byte gathered is counted local-vs-remote on
    weed_ec_repair_bytes_read_total, every rebuilt byte written on
    weed_ec_repair_bytes_written_total.

    `durable=True` fsyncs the rebuilt shard files before returning
    (the weedcrash contract for the generate/rebuild verbs: an acked
    shard set survives a crash — docs/ANALYSIS.md v3)."""
    if (rebuild_fn is None) != (fetch_fn is None):
        raise ValueError("rebuild_fn and fetch_fn must be injected together")
    if rebuild_fn is None:
        rebuild_fn, fetch_fn = _tpu_rebuild_fns(want_crcs=want_crcs)
    # rebuild tiles read one span from each of 10 FILES. Re-swept with
    # the staging ring (BENCH_r12): LOCAL rebuilds want fine tiles —
    # 512 KiB ran 3.7 GB/s vs 1.9 at the old 2 MiB (more in-flight
    # preads for the pool to overlap, page-cache-friendly spans) and
    # 1.33x the serial classic driver. REMOTE rack-gathers keep a big
    # tile: each tile costs one RPC per remote survivor, and 8x fewer
    # round-trips beats overlap granularity across a network hop.
    tile_bytes = tile_bytes or (
        4 * DEFAULT_TILE_BYTES if remote_readers else DEFAULT_TILE_BYTES // 2
    )
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    depth = pipeline_depth()
    remote_readers = dict(remote_readers or {})

    from seaweedfs_tpu.ec.ec_files import shard_presence, to_ext

    present, local_missing = shard_presence(base_file_name)
    local_ids = [i for i, p in enumerate(present) if p]
    # a shard readable remotely exists in the cluster: it can serve as
    # a survivor but must not be rebuilt here
    targets = tuple(i for i in local_missing if i not in remote_readers)
    if not targets:
        return []
    remote_ids = [i for i in remote_readers if not present[i]]
    if len(local_ids) + len(remote_ids) < DATA_SHARDS:
        raise ValueError(
            "too few shard files to rebuild: "
            f"{len(local_ids) + len(remote_ids)} of {DATA_SHARDS}"
        )
    if not local_ids:
        raise ValueError(
            "rebuild needs at least one local survivor (its size fixes "
            "the shard length)"
        )
    # prefer local survivors (free reads), top up from remote holders;
    # the decode matrix keeps the chosen set in ascending order — any
    # 10-of-14 subset reconstructs identical bytes
    survivors = tuple(
        sorted((local_ids + sorted(remote_ids))[:DATA_SHARDS])
    )
    shard_size = os.path.getsize(base_file_name + to_ext(local_ids[0]))

    out_fds: dict[int, int] = {}  # opened inside the try: no leak on ENOSPC
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=depth)
    # staging ring for survivor-gather tiles: gap gathers sub-allocate
    # contiguous [k, g_len] views out of one flat slot per tile
    ring = _StagingRing(
        depth + writer_threads + 1, DATA_SHARDS * tile_bytes
    )
    busy = {
        "read_s": 0.0,
        "stage_s": 0.0,
        "device_s": 0.0,
        "writeback_s": 0.0,
        "compute_s": 0.0,
        "write_s": 0.0,
    }
    busy_lock = threading.Lock()
    fetch_bucket = getattr(fetch_fn, "charges", "writeback_s")
    # (range offset, range length, [crc per target]) from the writer
    # pool, folded into whole-file CRCs after the join (append is
    # GIL-atomic; order restored by sorting on offset)
    crc_ranges: list[tuple[int, int, list[int]]] = []
    wall0 = time.perf_counter()
    # tracing plane: rebuild span (inherits the scrub/repair plane tag
    # when the caller's context carries one — cross-plane interference
    # is then directly measurable on /debug/traces)
    _sp = trace.span(
        "ec_stream.rebuild", nbytes=shard_size * max(1, len(targets))
    )
    _sp.__enter__()

    offsets = list(range(0, shard_size, tile_bytes))
    idx_lock = threading.Lock()
    idx_iter = iter(offsets)

    n_remote = sum(1 for i in survivors if not present[i])
    read_local = EC_REPAIR_BYTES_READ.labels("local")
    read_remote = EC_REPAIR_BYTES_READ.labels("remote")

    def reader():
        fds = {
            i: os.open(base_file_name + to_ext(i), os.O_RDONLY)
            for i in survivors
            if present[i]
        }
        # remote survivor fetches fan out per tile: serialized, a
        # tile's latency would be n_remote × RTT and a single slow
        # holder would stall the whole tile walk
        fetch_pool = (
            ThreadPoolExecutor(max_workers=min(n_remote, DATA_SHARDS))
            if n_remote > 1
            else None
        )

        def gather(g_off: int, g_len: int, dest: np.ndarray) -> np.ndarray:
            """One [k, g_len] survivor read at g_off into a staging-
            ring view — the only place rebuild bytes cross a disk or
            the network, so the repair accounting lives here."""
            tile = dest.reshape(DATA_SHARDS, g_len)
            futures = {}
            if fetch_pool is not None:
                futures = {
                    j: fetch_pool.submit(remote_readers[i], g_off, g_len)
                    for j, i in enumerate(survivors)
                    if i not in fds
                }
            for j, i in enumerate(survivors):
                if i in fds:
                    got = _pread_into(fds[i], tile[j], g_off)
                    read_local.inc(got)
                else:
                    fut = futures.get(j)
                    raw = (
                        fut.result()
                        if fut is not None
                        else remote_readers[i](g_off, g_len)
                    )
                    got = len(raw)
                    read_remote.inc(got)
                    if got == g_len:
                        tile[j] = np.frombuffer(raw, dtype=np.uint8)
                if got != g_len:
                    raise ValueError(
                        f"ec shard {i} truncated: expected {g_len} at "
                        f"{g_off}"
                    )
            return tile

        try:
            while True:
                with idx_lock:
                    offset = next(idx_iter, None)
                if offset is None:
                    return
                if session is not None:
                    # serve-first arbitration: degraded GET gathers in
                    # flight own the disks/links; repair waits (bounded)
                    session.yield_to_serving()
                step = min(tile_bytes, shard_size - offset)
                if session is not None:
                    covered, gaps = session.consume(offset, step)
                else:
                    covered, gaps = [], [(offset, step)]
                slot_id = -1
                if gaps:
                    got_slot = ring.acquire(pipe.stop)
                    if got_slot is None:
                        return
                    slot_id, buf = got_slot
                t0 = time.perf_counter()
                # parts: ("don", off, {target: bytes}) ride through as
                # bytes; ("raw", off, [k, n] tile) get decoded. Only the
                # gaps pay survivor reads — donated ranges moved zero
                # new bytes (arXiv:2205.11015's partial-repair shape).
                # Gap tiles sub-allocate contiguous views out of the
                # tile's ring slot (Σ gap bytes ≤ step, so they fit).
                parts: list = [
                    ("don", d_off, per_t) for d_off, per_t in covered
                ]
                cur = 0
                for g_off, g_len in gaps:
                    dest = buf[cur : cur + DATA_SHARDS * g_len]
                    cur += DATA_SHARDS * g_len
                    parts.append(("raw", g_off, gather(g_off, g_len, dest)))
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (offset, slot_id, parts), pipe.stop):
                    if slot_id >= 0:
                        ring.release(slot_id)
                    return
        finally:
            if fetch_pool is not None:
                # wait for in-flight remote fetches: the caller closes
                # the reader channels right after the driver returns,
                # and an RPC still running on a pool thread would see
                # its channel yanked (and leak the thread past return)
                fetch_pool.shutdown(wait=True, cancel_futures=True)
            for fd in fds.values():
                os.close(fd)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            _offset, slot_id, parts = item
            t0 = time.perf_counter()
            fetched = []
            for kind, off, payload in parts:
                crcs = None
                if kind == "h":
                    payload = fetch_fn(payload)
                    if isinstance(payload, tuple):
                        payload, crcs = payload
                fetched.append((kind, off, payload, crcs))
            t1 = time.perf_counter()
            if want_crcs:
                # donated ranges and declined-fused tiles: table-CRC
                # the bytes being written, charged as host compute
                from seaweedfs_tpu.util.crc import crc32c

                filled = []
                for kind, off, payload, crcs in fetched:
                    if crcs is None:
                        if kind == "don":
                            crcs = [crc32c(payload[i]) for i in targets]
                        else:
                            crcs = [
                                crc32c(np.ascontiguousarray(payload[j]).tobytes())
                                for j in range(len(targets))
                            ]
                    filled.append((kind, off, payload, crcs))
                fetched = filled
            t2 = time.perf_counter()
            for kind, off, payload, crcs in fetched:
                if kind == "don":
                    for i in targets:
                        _pwrite_full(out_fds[i], payload[i], off)
                        EC_REPAIR_BYTES_WRITTEN.inc(len(payload[i]))
                    length = len(payload[targets[0]]) if targets else 0
                else:
                    length = 0
                    for j, i in enumerate(targets):
                        row = np.ascontiguousarray(payload[j])
                        _pwrite_full(out_fds[i], row, off)
                        EC_REPAIR_BYTES_WRITTEN.inc(len(row))
                        length = len(row)
                if want_crcs and crcs is not None:
                    crc_ranges.append((off, length, [int(c) for c in crcs]))
            t3 = time.perf_counter()
            if slot_id >= 0:
                ring.release(slot_id)
            _charge(busy, busy_lock, fetch_bucket, t1 - t0)
            _charge(busy, busy_lock, "compute_s", t2 - t1)
            _charge(busy, busy_lock, "write_s", t3 - t2)

    ok = False
    try:
        for i in targets:
            out_fds[i] = os.open(
                base_file_name + to_ext(i),
                os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                0o644,
            )
        for fd in out_fds.values():
            _preallocate(fd, shard_size)
        for _ in range(reader_threads):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(len(offsets)):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            offset, slot_id, parts = item
            t0 = time.perf_counter()
            parts = [
                (
                    ("h", off, rebuild_fn(survivors, targets, payload))
                    if kind == "raw"
                    else (kind, off, payload)
                )
                for kind, off, payload in parts
            ]
            _charge(busy, busy_lock, "device_s", time.perf_counter() - t0)
            if not _q_put(write_q, (offset, slot_id, parts), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)  # may re-raise a stage error
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fd in out_fds.values():
                    try:
                        if durable and ok and not pipe.errors:
                            # crash contract (weedcrash, docs/ANALYSIS.md
                            # v3): a rebuild acked to its caller must
                            # survive power loss — pin the shard bytes
                            # before the fds close and the ack leaves;
                            # a FAILED fsync fails the rebuild (below)
                            # rather than acking page-cache-only bytes
                            try:
                                os.fsync(fd)
                            except OSError as e:
                                if fsync_err is None:
                                    fsync_err = e
                        os.close(fd)
                    except OSError:
                        pass
                if not ok or pipe.errors or fsync_err is not None:
                    # half-written targets must not survive: a later
                    # shard_presence would count the garbage files as
                    # valid shards and silently skip rebuilding them
                    # (e.g. ec.rebuild's full-copy fallback retry)
                    for i in targets:
                        try:
                            os.remove(base_file_name + to_ext(i))
                        except OSError:
                            pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                # an ENOSPC surfacing mid-stream must not skip the
                # stats nor leak any fd (the reader pool closes its own
                # survivor fds in its thread's finally)
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    stats["pipeline_depth"] = depth
                    stats["ring_slots"] = ring.slots
                    if (
                        want_crcs
                        and ok
                        and not pipe.errors
                        and fsync_err is None
                    ):
                        stats["shard_crcs"] = _fold_rebuild_crcs(
                            targets, crc_ranges
                        )
                    if session is not None:
                        stats["donated_bytes"] = session.donated_bytes
                        stats["used_donated_bytes"] = (
                            session.used_donated_bytes
                        )
                        stats["serve_yields"] = session.yields
                _trace_stages(_sp, busy)
                if session is not None and _sp:
                    _sp.annotate("donated_bytes", session.used_donated_bytes)
                    _sp.annotate("serve_yields", session.yields)
                # a stage error re-raised by pipe.finish() is live in
                # this finally; hand it to the span so a failed drive
                # is distinguishable from a clean one in /debug/traces
                _sp.__exit__(*sys.exc_info())
    return list(targets)


def _fold_rebuild_crcs(
    targets: tuple[int, ...], crc_ranges: list[tuple[int, int, list[int]]]
) -> dict[int, int]:
    """{target shard id: whole-file CRC-32C} from the writer pool's
    per-range CRCs: ranges land in any order (positioned writes), so
    sort by offset and fold with crc32c_combine."""
    from seaweedfs_tpu.util.crc import crc32c_combine

    acc = {i: 0 for i in targets}
    for _off, length, crcs in sorted(crc_ranges, key=lambda r: r[0]):
        for j, i in enumerate(targets):
            acc[i] = crc32c_combine(acc[i], crcs[j], length)
    return acc


def _trace_stages(sp, busy: dict) -> None:
    """Fold the driver's per-stage busy thread-seconds onto its span as
    the three pipeline stages an operator reasons about: reader-pool
    (disk/remote reads), compute (staging + device dispatch/drain +
    host codec), writer-pool (shard pwritev)."""
    sp.add_stages(
        {
            "reader-pool": busy.get("read_s", 0.0),
            "compute": (
                busy.get("stage_s", 0.0)
                + busy.get("device_s", 0.0)
                + busy.get("writeback_s", 0.0)
                + busy.get("compute_s", 0.0)
            ),
            "writer-pool": busy.get("write_s", 0.0),
        }
    )


def _finish_stats(
    stats: dict,
    busy: dict,
    wall0: float,
    reader_threads: int = 1,
    writer_threads: int = 1,
) -> None:
    """Per-stage busy thread-seconds + wall and the unattributed
    remainder. The PIPELINE stages (read/dispatch/fetch/write) run in
    thread POOLS, so a stage's Σ can exceed wall (overlap across
    threads) — the wall a stage explains is its total divided by its
    pool width. flush_s is different: it is the SERIAL post-pipeline
    close of the raw fds appended to the wall (≈0 now that nothing is
    buffered), so it subtracts separately. loop_s = wall − flush − max
    per-thread stage share: the honest "pipeline was idle / Python
    glue" residue for a bench line to carry (clamped at 0 — pool
    accounting is approximate)."""
    wall = time.perf_counter() - wall0
    flush = busy.get("flush_s", 0.0)
    widths = {
        "read_s": reader_threads,
        "writeback_s": writer_threads,
        "compute_s": writer_threads,
        "write_s": writer_threads,
    }
    pipeline_max = max(
        (
            v / widths.get(k, 1)
            for k, v in busy.items()
            if k != "flush_s"
        ),
        default=0.0,
    )
    stats.update({k: round(v, 4) for k, v in busy.items()})
    stats["wall_s"] = round(wall, 4)
    stats["loop_s"] = round(max(0.0, wall - flush - pipeline_max), 4)
    # busy thread-seconds in excess of wall = stage time that ran
    # CONCURRENTLY with another stage: the mechanical proof that
    # reader / device / writer work actually overlapped this run
    # (0 would mean the pipeline degenerated to a serial chain)
    stats["overlap_s"] = round(
        max(
            0.0,
            sum(v for k, v in busy.items() if k != "flush_s")
            - (wall - flush),
        ),
        4,
    )
    stats["reader_threads"] = reader_threads
    stats["writer_threads"] = writer_threads


# --- default TPU kernel stages ---------------------------------------------


def _swar_ok(step: int) -> bool:
    from seaweedfs_tpu.ec.codec_tpu import _SWAR_MIN_BYTES, _on_tpu

    return step % 1024 == 0 and step >= _SWAR_MIN_BYTES and _on_tpu()


def _fetch(handle) -> np.ndarray:
    """Block a dispatched kernel handle into a host uint8 array — or
    (uint8 array, crc uint32 array) when the dispatch fused the CRC
    pass (the driver splits on the tuple)."""
    import jax

    out, swar, fused_crc = handle
    if fused_crc:
        dev, crcs = out
        host = np.asarray(jax.device_get(dev))
        return host.view(np.uint8), np.asarray(jax.device_get(crcs))
    host = np.asarray(jax.device_get(out))
    return host.view(np.uint8) if swar else host


def _crc_ok(step: int, want_crcs: bool) -> bool:
    from seaweedfs_tpu.ec import crc_kernel

    return want_crcs and crc_kernel.crc_supported(step)


def _tpu_encode_fns(want_crcs: bool = False):
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)
    # donate the H2D staging buffer: the [10, n32] tile is dead the
    # moment the kernel has read it, and with 3 tiles in flight XLA
    # recycling the donated extent keeps the deepened window from
    # growing HBM residency per tile
    encode_u32_don = jax.jit(
        lambda u32: kern.encode_u32(u32), donate_argnums=0
    )
    # fused encode+CRC program (ec/crc_kernel.py rides the same
    # dispatch): parity AND all 14 per-row CRCs come back from one
    # device pass, so the host never re-reads parity bytes to
    # checksum them
    encode_u32_crc_don = jax.jit(
        lambda u32: kern.encode_u32_crc(u32), donate_argnums=0
    )

    def parity_fn(tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        fused_crc = _crc_ok(tile.shape[1], want_crcs)
        if swar and fused_crc:
            u32 = jnp.asarray(tile.view(np.uint32))  # async H2D
            out = encode_u32_crc_don(u32)
        elif swar:
            u32 = jnp.asarray(tile.view(np.uint32))  # async H2D
            out = encode_u32_don(u32)  # async dispatch
        else:
            out = kern.encode(jnp.asarray(tile))
            fused_crc = False
        return out, swar, fused_crc

    return parity_fn, _fetch


def _tpu_rebuild_fns(want_crcs: bool = False):
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(DATA_SHARDS, PARITY_SHARDS)
    recon_don = jax.jit(
        lambda s, t, u32: kern.reconstruct_u32(s, t, u32),
        static_argnums=(0, 1),
        donate_argnums=2,
    )
    recon_crc_don = jax.jit(
        lambda s, t, u32: kern.reconstruct_u32_crc(s, t, u32),
        static_argnums=(0, 1),
        donate_argnums=2,
    )

    def rebuild_fn(survivors, targets, tile: np.ndarray):
        swar = _swar_ok(tile.shape[1])
        fused_crc = _crc_ok(tile.shape[1], want_crcs)
        if swar and fused_crc:
            u32 = jnp.asarray(tile.view(np.uint32))
            out = recon_crc_don(tuple(survivors), tuple(targets), u32)
        elif swar:
            u32 = jnp.asarray(tile.view(np.uint32))
            out = recon_don(tuple(survivors), tuple(targets), u32)
        else:
            out = kern.reconstruct(survivors, targets, jnp.asarray(tile))
            fused_crc = False
        return out, swar, fused_crc

    return rebuild_fn, _fetch


# --- mesh-batched encode driver ---------------------------------------------


def _read_tile_into(
    fd: int, dat_size: int, row_off: int, block: int, batch_off: int,
    step: int, dest: np.ndarray,
) -> None:
    """Fill dest [10, step] (ring-slot views) with one volume's tile of
    the .dat, zero-padded past EOF — the single home of the batch
    reader's striping math (same layout the single-volume reader
    inlines). Per-row reads even for full rows: dest rows are strided
    views of the batch slot, so there is no contiguous span to
    coalesce into one pread here."""
    for i in range(DATA_SHARDS):
        row = dest[i]
        off = row_off + i * block + batch_off
        n = max(0, min(step, dat_size - off))
        if n < step:
            row[n:] = 0
        if n:
            got = _pread_into(fd, row[:n], off)
            if got < n:
                row[got:n] = 0


def stream_write_ec_files_batch(
    base_file_names: list[str],
    codec=None,
    tile_bytes: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    stats: dict | None = None,
    durable: bool = False,
    want_crcs: bool = False,
    reader_threads: int | None = None,
    writer_threads: int | None = None,
) -> None:
    """Pipelined batch-of-volumes encode through the mesh codec: N
    sealed .dat files → N shard sets, byte-identical per volume to
    write_ec_files, with disk reads, host staging, the sharded device
    program (parallel/mesh_codec.encode_batch_u32[_crc] under
    shard_map), device drain, and shard pwritevs all overlapped by the
    same staging-ring pipeline the single-volume driver runs. This is
    how a batch of SMALL volumes saturates one chip (the per-volume
    dispatch was latency-bound) and a mesh of chips splits the stream
    axis of large ones.

    codec=None self-provisions a MeshCodec whose 'vol' axis is the gcd
    of batch size and device count (any batch shards cleanly); when
    jax itself is unavailable the whole batch falls back to the
    single-volume host-codec driver per volume — byte-identical, just
    unbatched. WEED_EC_PIPELINE_BATCH caps volumes per dispatch round
    (ring memory = slots x batch x 10 x tile bytes).

    want_crcs=True lands `shard_crcs` in stats: one 14-entry CRC-32C
    list per volume (fused on-mesh for full-width rounds — including
    the stripe-axis CRC composition collective — host table CRC for
    the short tail round)."""
    if not base_file_names:
        return
    limit = pipeline_batch_limit()
    if limit and len(base_file_names) > limit:
        all_crcs: list = []
        for i in range(0, len(base_file_names), limit):
            chunk_stats: dict = {}
            stream_write_ec_files_batch(
                base_file_names[i : i + limit],
                # each chunk self-provisions a mesh that fits ITS size
                # (gcd sizing): a caller codec built for the WHOLE
                # batch need not divide a chunk — passing it through
                # would brick the verb the moment the memory-cap knob
                # splits the batch unevenly
                codec=None,
                tile_bytes=tile_bytes,
                large_block_size=large_block_size,
                small_block_size=small_block_size,
                stats=chunk_stats,
                durable=durable,
                want_crcs=want_crcs,
                reader_threads=reader_threads,
                writer_threads=writer_threads,
            )
            if want_crcs:
                all_crcs.extend(chunk_stats.get("shard_crcs", []))
            if stats is not None:
                for k, v in chunk_stats.items():
                    if isinstance(v, float):
                        # stage seconds accumulate across chunks
                        stats[k] = round(stats.get(k, 0.0) + v, 4)
                    elif k != "shard_crcs":
                        # structural fields (pipeline_depth, mesh,
                        # ring_slots, thread counts): last chunk's
                        # values — dropping them would break every
                        # consumer the docs promise them to
                        stats[k] = v
        if stats is not None:
            stats["batch_volumes"] = len(base_file_names)
            if want_crcs:
                stats["shard_crcs"] = all_crcs
        return
    if codec is None:
        try:
            codec = _default_mesh_codec(len(base_file_names))
        except ImportError:
            # no jax at all: the host-codec single-volume pipeline is
            # the byte-identical fallback seam
            from seaweedfs_tpu.ec.codec import new_encoder

            rs = new_encoder()
            all_crcs = []
            for base in base_file_names:
                s: dict = {}
                parity_fn, fetch_fn = local_encode_fns(rs, want_crcs=want_crcs)
                stream_write_ec_files(
                    base,
                    tile_bytes=tile_bytes,
                    large_block_size=large_block_size,
                    small_block_size=small_block_size,
                    parity_fn=parity_fn,
                    fetch_fn=fetch_fn,
                    stats=s,
                    durable=durable,
                    want_crcs=want_crcs,
                )
                if want_crcs:
                    all_crcs.append(s.get("shard_crcs"))
            if stats is not None:
                stats["fallback"] = "host"
                if want_crcs:
                    stats["shard_crcs"] = all_crcs
            return
    _stream_batch_chunk(
        base_file_names, codec, tile_bytes, large_block_size,
        small_block_size, stats, durable, want_crcs, reader_threads,
        writer_threads,
    )


def _default_mesh_codec(batch: int):
    """MeshCodec over all devices with the 'vol' axis sized to
    gcd(batch, devices) so any batch shards cleanly (the
    BatchGenerate verb's mesh recipe, now owned by the driver)."""
    import math

    import jax

    from seaweedfs_tpu.parallel import MeshCodec, make_mesh

    devices = jax.devices()
    vol_axis = math.gcd(batch, len(devices))
    return MeshCodec(make_mesh(devices, stripe=len(devices) // vol_axis))


class _HostBatchCodec:
    """Marker codec routing the batch REBUILD driver to its host arm
    (_rebuild_batch_chunk_host) on hosts whose only jax devices are
    CPU: the SWAR Pallas kernels run interpreted there, orders of
    magnitude under the host RS backends, so the batch win must come
    from the host side instead — ONE shared pipeline (one thread-pool
    spin-up, one staging ring, per-(volume, tile) work items) for the
    whole group, where the serial path pays the driver's fixed cost
    once per volume. Byte-identical to the per-volume path: same
    cached decode rows, same survivor order, bytewise GF math."""

    def __init__(self, rs):
        import types

        self.rs = rs
        # group-chunking code only reads devices.shape: (1, 1)
        self.mesh = types.SimpleNamespace(
            devices=np.empty((1, 1), dtype=object)
        )


def _stream_batch_chunk(
    bases: list[str], codec, tile_bytes, large_block_size, small_block_size,
    stats, durable, want_crcs, reader_threads, writer_threads,
) -> None:
    from seaweedfs_tpu.ec.ec_files import (
        iter_ec_tiles, shard_file_size, to_ext,
    )

    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    depth = pipeline_depth()
    b = len(bases)
    vol_axis = codec.mesh.devices.shape[0]
    stripe = codec.mesh.devices.shape[1]
    if b % vol_axis:
        raise ValueError(
            f"batch of {b} volumes does not shard over the mesh's "
            f"{vol_axis}-way 'vol' axis"
        )

    sizes = [os.path.getsize(base + ".dat") for base in bases]
    tiles = [
        list(
            iter_ec_tiles(size, tile_bytes, large_block_size, small_block_size)
        )
        for size in sizes
    ]
    rounds = max((len(ts) for ts in tiles), default=0)
    if not rounds:
        # all .dat files empty: 14 empty shard files each — fsynced
        # when durable, so the caller's .ecx publish can never outlive
        # shard files a crash could drop
        from seaweedfs_tpu.util import durable as _durable

        for base in bases:
            for i in range(TOTAL_SHARDS):
                open(base + to_ext(i), "wb").close()
                if durable:
                    _durable.fsync_path(base + to_ext(i))
        if stats is not None and want_crcs:
            stats["shard_crcs"] = [[0] * TOTAL_SHARDS for _ in bases]
        return
    # one static tile width for every round (finished volumes ride as
    # zero-step entries whose output is discarded), rounded so the u32
    # lane count splits over the stripe axis in whole SWAR-friendly
    # chunks — shapes stay static, the mesh program compiles once
    max_step = max(step for ts in tiles for _, _, _, step in ts)
    gran = 4 * 1024 * stripe
    width = -(-max_step // gran) * gran
    # fused CRC needs power-of-two lanes per device (crc_kernel); the
    # tail rounds (step < width) are host-checksummed regardless
    fused_crc = want_crcs and codec.crc_supported(width)
    step_of = [
        [(ts[r][3] if r < len(ts) else 0) for ts in tiles]
        for r in range(rounds)
    ]
    out_offs = []  # [rounds][volume] output offset
    acc = [0] * b
    for r in range(rounds):
        out_offs.append(list(acc))
        for v in range(b):
            acc[v] += step_of[r][v]

    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=depth)
    ring = _StagingRing(
        depth + writer_threads + 1, b * DATA_SHARDS * width
    )
    busy = {
        "read_s": 0.0,
        "stage_s": 0.0,
        "device_s": 0.0,
        "writeback_s": 0.0,
        "compute_s": 0.0,
        "write_s": 0.0,
    }
    busy_lock = threading.Lock()
    round_crcs: list = [None] * rounds
    wall0 = time.perf_counter()
    _sp = trace.span("ec_stream.encode_batch", nbytes=sum(sizes))
    _sp.__enter__()

    idx_lock = threading.Lock()
    idx_iter = iter(range(rounds))
    out_fds: list[list[int]] = []

    def reader():
        fds = [os.open(base + ".dat", os.O_RDONLY) for base in bases]
        try:
            while True:
                with idx_lock:
                    r = next(idx_iter, None)
                if r is None:
                    return
                got_slot = ring.acquire(pipe.stop)
                if got_slot is None:
                    return
                slot_id, buf = got_slot
                t0 = time.perf_counter()
                buf3 = buf[: b * DATA_SHARDS * width].reshape(
                    b, DATA_SHARDS, width
                )
                for v in range(b):
                    if r >= len(tiles[v]):
                        continue  # volume done: zero-step, output discarded
                    row_off, block, batch_off, step = tiles[v][r]
                    _read_tile_into(
                        fds[v], sizes[v], row_off, block, batch_off, step,
                        buf3[v, :, :step],
                    )
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (r, slot_id, buf3), pipe.stop):
                    ring.release(slot_id)
                    return
        finally:
            for fd in fds:
                os.close(fd)

    def writer():
        import jax

        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            r, slot_id, buf3, handle = item
            t0 = time.perf_counter()
            if fused_crc:
                parity_dev, crcs_dev = handle
                crcs = np.asarray(jax.device_get(crcs_dev))
            else:
                parity_dev, crcs = handle, None
            parity = (
                np.asarray(jax.device_get(parity_dev))
                .view(np.uint8)
                .reshape(b, PARITY_SHARDS, width)
            )
            t1 = time.perf_counter()
            vol_crcs: list = [None] * b
            if want_crcs:
                from seaweedfs_tpu.util.crc import crc32c

                for v in range(b):
                    step = step_of[r][v]
                    if not step:
                        continue
                    if crcs is not None and step == width:
                        vol_crcs[v] = [int(c) for c in crcs[v]]
                    else:
                        # tail round: the fused CRC would cover the
                        # padded width; table-CRC the written bytes
                        vol_crcs[v] = [
                            crc32c(buf3[v, i, :step].tobytes())
                            for i in range(DATA_SHARDS)
                        ] + [
                            crc32c(
                                np.ascontiguousarray(
                                    parity[v, p, :step]
                                ).tobytes()
                            )
                            for p in range(PARITY_SHARDS)
                        ]
            t2 = time.perf_counter()
            for v in range(b):
                step = step_of[r][v]
                if not step:
                    continue
                off = out_offs[r][v]
                for i in range(DATA_SHARDS):
                    _pwrite_full(out_fds[v][i], buf3[v, i, :step], off)
                for p in range(PARITY_SHARDS):
                    _pwrite_full(
                        out_fds[v][DATA_SHARDS + p],
                        np.ascontiguousarray(parity[v, p, :step]),
                        off,
                    )
            t3 = time.perf_counter()
            if want_crcs:
                round_crcs[r] = vol_crcs
            ring.release(slot_id)
            _charge(busy, busy_lock, "writeback_s", t1 - t0)
            _charge(busy, busy_lock, "compute_s", t2 - t1)
            _charge(busy, busy_lock, "write_s", t3 - t2)

    ok = False
    try:
        for v, base in enumerate(bases):
            fds = []
            out_fds.append(fds)
            for i in range(TOTAL_SHARDS):
                fds.append(
                    os.open(
                        base + to_ext(i),
                        os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                        0o644,
                    )
                )
            size = shard_file_size(
                sizes[v], large_block_size, small_block_size
            )
            for fd in fds:
                _preallocate(fd, size)
        for _ in range(min(reader_threads, rounds)):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(rounds):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            r, slot_id, buf3 = item
            t0 = time.perf_counter()
            # staging: the u32 lane view is free host-side; device_put
            # lays the batch out P('vol', None, 'stripe') over the mesh
            vols = codec.shard_volumes(buf3.view(np.uint32))
            t1 = time.perf_counter()
            handle = (
                codec.encode_batch_u32_crc(vols)
                if fused_crc
                else codec.encode_batch_u32(vols)
            )
            t2 = time.perf_counter()
            _charge(busy, busy_lock, "stage_s", t1 - t0)
            _charge(busy, busy_lock, "device_s", t2 - t1)
            if not _q_put(write_q, (r, slot_id, buf3, handle), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fds in out_fds:
                    for fd in fds:
                        try:
                            if durable and ok and not pipe.errors:
                                try:
                                    os.fsync(fd)
                                except OSError as e:
                                    if fsync_err is None:
                                        fsync_err = e
                            os.close(fd)
                        except OSError:
                            pass
                if not ok or pipe.errors or fsync_err is not None:
                    # same abort contract as the single-volume driver:
                    # no partial shard set may survive for ANY volume
                    for base in bases:
                        for i in range(TOTAL_SHARDS):
                            try:
                                os.remove(base + to_ext(i))
                            except OSError:
                                pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    stats["pipeline_depth"] = depth
                    stats["ring_slots"] = ring.slots
                    stats["batch_volumes"] = b
                    stats["mesh"] = {"vol": vol_axis, "stripe": stripe}
                    if (
                        want_crcs
                        and ok
                        and not pipe.errors
                        and fsync_err is None
                    ):
                        stats["shard_crcs"] = _fold_batch_crcs(
                            b, step_of, round_crcs
                        )
                _trace_stages(_sp, busy)
                _sp.__exit__(*sys.exc_info())


def _fold_batch_crcs(
    b: int, step_of: list[list[int]], round_crcs: list
) -> list[list[int]]:
    """Per-volume 14-entry whole-file CRCs from the per-round writer
    records, folded in round order."""
    from seaweedfs_tpu.util.crc import crc32c_combine

    out = []
    for v in range(b):
        acc = [0] * TOTAL_SHARDS
        for r, vol_crcs in enumerate(round_crcs):
            step = step_of[r][v]
            if not step or vol_crcs is None or vol_crcs[v] is None:
                continue
            for i in range(TOTAL_SHARDS):
                acc[i] = crc32c_combine(acc[i], vol_crcs[v][i], step)
        out.append(acc)
    return out


def stream_rebuild_ec_files_batch(
    base_file_names: list[str],
    codec=None,
    tile_bytes: int | None = None,
    stats: dict | None = None,
    durable: bool = False,
    want_crcs: bool = False,
    reader_threads: int | None = None,
    writer_threads: int | None = None,
) -> list[list[int]]:
    """Rebuild N volumes' missing shard files through ONE sharded mesh
    program per tile round — the rebuild-side sibling of
    stream_write_ec_files_batch. The RepairScheduler's common case is a
    node loss surfacing many small EC volumes missing the SAME shard
    ids at once; rebuilding them one dispatch per volume is
    latency-bound exactly like the small-volume encode was. Here each
    tile round stacks one [k, W] survivor tile per volume into a
    [B, k, W/4]-lane batch laid out P('vol', None, 'stripe') and runs
    parallel/mesh_codec.reconstruct_batch_u32 once.

    Volumes are grouped by their (survivors, targets) signature — each
    group compiles one decode program; mixed-damage batches run one
    group after another, still batched within each. Every survivor must
    be LOCAL: the rack-gather/remote-reader and repair-session features
    stay with the single-volume driver (callers with remote survivors
    route there). Output bytes per volume are identical to
    rebuild_ec_files (RS determinism over the same ascending survivor
    choice).

    want_crcs=True lands `shard_crcs` in stats: one {rebuilt shard id:
    whole-file CRC-32C} dict per volume, in base_file_names order
    (host table CRCs per round — reconstruct has no fused CRC tier —
    folded with crc32c_combine). Returns the per-volume rebuilt id
    lists in base_file_names order; volumes with nothing missing
    return [].

    `durable=True` fsyncs every rebuilt shard before returning; a
    failed chunk removes ALL its volumes' target files (the abort
    contract scrub relies on: no partial rebuilt shard survives)."""
    from seaweedfs_tpu.ec.ec_files import shard_presence, to_ext

    results: list[list[int]] = [[] for _ in base_file_names]
    if not base_file_names:
        return results
    groups: dict[tuple, list[int]] = {}
    sigs: list[tuple | None] = []
    for i, base in enumerate(base_file_names):
        present, missing = shard_presence(base)
        targets = tuple(missing)
        if not targets:
            sigs.append(None)
            continue
        local_ids = [s for s, p in enumerate(present) if p]
        if len(local_ids) < DATA_SHARDS:
            raise ValueError(
                f"too few local shard files to batch-rebuild {base}: "
                f"{len(local_ids)} of {DATA_SHARDS}"
            )
        # same ascending first-k survivor choice as the single-volume
        # driver with no remote holders: byte-identical output
        survivors = tuple(sorted(local_ids)[:DATA_SHARDS])
        sig = (survivors, targets)
        sigs.append(sig)
        groups.setdefault(sig, []).append(i)

    if not groups:
        if stats is not None:
            stats["batch_volumes"] = len(base_file_names)
            stats["batch_groups"] = 0
            if want_crcs:
                stats["shard_crcs"] = [{} for _ in base_file_names]
        return results

    if codec is None:
        try:
            import jax

            if all(d.platform == "cpu" for d in jax.devices()):
                # no accelerator: the interpreted Pallas kernels lose
                # to the host backends by orders of magnitude, so run
                # the batch through the host arm (same grouping and
                # staging, one concatenated matrix apply per round)
                from seaweedfs_tpu.ec.codec import new_encoder

                try:
                    codec = _HostBatchCodec(new_encoder(backend="native"))
                except (ImportError, ValueError):
                    codec = _HostBatchCodec(new_encoder(backend="cpu"))
            else:
                codec = _default_mesh_codec(
                    max(len(idxs) for idxs in groups.values())
                )
        except ImportError:
            # no jax: the single-volume pipeline per volume is the
            # byte-identical fallback seam (it self-selects the host
            # codec the same way rebuild_ec_files does)
            from seaweedfs_tpu.ec import ec_files as _ec_files

            all_crcs: list = []
            for i, base in enumerate(base_file_names):
                if sigs[i] is None:
                    all_crcs.append({})
                    continue
                s: dict = {}
                results[i] = _ec_files.rebuild_ec_files(
                    base, durable=durable, stats=s, want_crcs=want_crcs
                )
                all_crcs.append(s.get("shard_crcs") or {})
            if stats is not None:
                stats["fallback"] = "host"
                stats["batch_volumes"] = len(base_file_names)
                stats["batch_groups"] = len(groups)
                if want_crcs:
                    stats["shard_crcs"] = all_crcs
            return results

    limit = pipeline_batch_limit()
    crcs_by_vol: dict[int, dict] = {}
    float_acc: dict[str, float] = {}
    last_struct: dict = {}
    for (survivors, targets), idxs in groups.items():
        chunks = (
            [idxs[i : i + limit] for i in range(0, len(idxs), limit)]
            if limit
            else [idxs]
        )
        for chunk in chunks:
            chunk_stats: dict = {}
            # each chunk self-provisions a mesh that fits ITS size when
            # the caller passed none originally — but a caller codec is
            # honored only if the chunk shards over its vol axis
            chunk_codec = codec
            if len(chunk) % codec.mesh.devices.shape[0]:
                chunk_codec = _default_mesh_codec(len(chunk))
            _rebuild_batch_chunk(
                [base_file_names[i] for i in chunk],
                chunk_codec, survivors, targets, tile_bytes, chunk_stats,
                durable, want_crcs, reader_threads, writer_threads,
            )
            for i in chunk:
                results[i] = list(targets)
            if want_crcs:
                for i, crcs in zip(
                    chunk, chunk_stats.get("shard_crcs") or []
                ):
                    crcs_by_vol[i] = crcs
            for k, v in chunk_stats.items():
                if isinstance(v, float):
                    float_acc[k] = round(float_acc.get(k, 0.0) + v, 4)
                elif k != "shard_crcs":
                    last_struct[k] = v
    if stats is not None:
        stats.update(float_acc)
        stats.update(last_struct)
        stats["batch_volumes"] = len(base_file_names)
        stats["batch_groups"] = len(groups)
        if want_crcs:
            stats["shard_crcs"] = [
                crcs_by_vol.get(i, {}) for i in range(len(base_file_names))
            ]
    return results


def _rebuild_batch_chunk(
    bases: list[str], codec, survivors: tuple[int, ...],
    targets: tuple[int, ...], tile_bytes, stats, durable, want_crcs,
    reader_threads, writer_threads,
) -> None:
    """One (survivors, targets)-homogeneous chunk through the mesh:
    the rebuild-side mirror of _stream_batch_chunk. Reads [k, step]
    survivor tiles per volume into a [B, k, W] staging slot, runs
    reconstruct_batch_u32 once per round, pwrites the rebuilt target
    rows. Same abort contract: any failure removes every volume's
    target files."""
    if isinstance(codec, _HostBatchCodec):
        return _rebuild_batch_chunk_host(
            bases, codec.rs, survivors, targets, tile_bytes, stats,
            durable, want_crcs, reader_threads, writer_threads,
        )
    from seaweedfs_tpu.ec.ec_files import to_ext

    # local rebuilds want the fine tile (BENCH_r12: more in-flight
    # preads to overlap, page-cache-friendly spans) — and the batch arm
    # is local-survivor-only by contract
    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES // 2
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    depth = pipeline_depth()
    b = len(bases)
    vol_axis = codec.mesh.devices.shape[0]
    stripe = codec.mesh.devices.shape[1]
    if b % vol_axis:
        raise ValueError(
            f"batch of {b} volumes does not shard over the mesh's "
            f"{vol_axis}-way 'vol' axis"
        )

    sizes = [
        os.path.getsize(base + to_ext(survivors[0])) for base in bases
    ]
    rounds = max(-(-size // tile_bytes) for size in sizes)
    if not rounds:
        # all-empty shard sets: rebuilt targets are empty files too
        from seaweedfs_tpu.util import durable as _durable

        for base in bases:
            for t in targets:
                open(base + to_ext(t), "wb").close()
                if durable:
                    _durable.fsync_path(base + to_ext(t))
        if stats is not None:
            stats["batch_volumes"] = b
            if want_crcs:
                stats["shard_crcs"] = [
                    {t: 0 for t in targets} for _ in bases
                ]
        return
    step_of = [
        [
            max(0, min(tile_bytes, sizes[v] - r * tile_bytes))
            for v in range(b)
        ]
        for r in range(rounds)
    ]
    # one static tile width for every round, rounded so the u32 lane
    # count splits over the stripe axis in whole SWAR-friendly chunks
    max_step = max(step for row in step_of for step in row)
    gran = 4 * 1024 * stripe
    width = -(-max_step // gran) * gran

    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=depth)
    ring = _StagingRing(
        depth + writer_threads + 1, b * DATA_SHARDS * width
    )
    busy = {
        "read_s": 0.0,
        "stage_s": 0.0,
        "device_s": 0.0,
        "writeback_s": 0.0,
        "compute_s": 0.0,
        "write_s": 0.0,
    }
    busy_lock = threading.Lock()
    round_crcs: list = [None] * rounds
    wall0 = time.perf_counter()
    _sp = trace.span(
        "ec_stream.rebuild_batch",
        nbytes=sum(sizes) * max(1, len(targets)),
    )
    _sp.__enter__()

    idx_lock = threading.Lock()
    idx_iter = iter(range(rounds))
    out_fds: list[dict[int, int]] = []
    read_local = EC_REPAIR_BYTES_READ.labels("local")

    def reader():
        fds = [
            [
                os.open(base + to_ext(s), os.O_RDONLY)
                for s in survivors
            ]
            for base in bases
        ]
        try:
            while True:
                with idx_lock:
                    r = next(idx_iter, None)
                if r is None:
                    return
                got_slot = ring.acquire(pipe.stop)
                if got_slot is None:
                    return
                slot_id, buf = got_slot
                t0 = time.perf_counter()
                buf3 = buf[: b * DATA_SHARDS * width].reshape(
                    b, DATA_SHARDS, width
                )
                off = r * tile_bytes
                for v in range(b):
                    step = step_of[r][v]
                    if not step:
                        continue  # volume done: output discarded
                    tile = buf3[v, :, :step]
                    for j in range(DATA_SHARDS):
                        got = _pread_into(fds[v][j], tile[j], off)
                        read_local.inc(got)
                        if got != step:
                            raise ValueError(
                                f"ec shard {survivors[j]} truncated: "
                                f"expected {step} at {off} "
                                f"({bases[v] + to_ext(survivors[j])})"
                            )
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(read_q, (r, slot_id, buf3), pipe.stop):
                    ring.release(slot_id)
                    return
        finally:
            for vol_fds in fds:
                for fd in vol_fds:
                    os.close(fd)

    def writer():
        import jax

        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            r, slot_id, buf3, handle = item
            t0 = time.perf_counter()
            rebuilt = (
                np.asarray(jax.device_get(handle))
                .view(np.uint8)
                .reshape(b, len(targets), width)
            )
            t1 = time.perf_counter()
            vol_crcs: list = [None] * b
            if want_crcs:
                from seaweedfs_tpu.util.crc import crc32c

                for v in range(b):
                    step = step_of[r][v]
                    if not step:
                        continue
                    # no fused CRC tier for reconstruct: host table
                    # CRC the rebuilt rows (charged to compute_s)
                    vol_crcs[v] = [
                        crc32c(
                            np.ascontiguousarray(
                                rebuilt[v][t, :step]
                            ).tobytes()
                        )
                        for t in range(len(targets))
                    ]
            t2 = time.perf_counter()
            off = r * tile_bytes
            for v in range(b):
                step = step_of[r][v]
                if not step:
                    continue
                for t, tid in enumerate(targets):
                    _pwrite_full(
                        out_fds[v][tid],
                        np.ascontiguousarray(rebuilt[v][t, :step]),
                        off,
                    )
                    EC_REPAIR_BYTES_WRITTEN.inc(step)
            t3 = time.perf_counter()
            if want_crcs:
                round_crcs[r] = vol_crcs
            ring.release(slot_id)
            _charge(busy, busy_lock, "writeback_s", t1 - t0)
            _charge(busy, busy_lock, "compute_s", t2 - t1)
            _charge(busy, busy_lock, "write_s", t3 - t2)

    ok = False
    try:
        for v, base in enumerate(bases):
            fds: dict[int, int] = {}
            out_fds.append(fds)
            for tid in targets:
                fds[tid] = os.open(
                    base + to_ext(tid),
                    os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                    0o644,
                )
            for fd in fds.values():
                _preallocate(fd, sizes[v])
        for _ in range(min(reader_threads, rounds)):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(rounds):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            r, slot_id, buf3 = item
            t0 = time.perf_counter()
            vols = codec.shard_volumes(buf3.view(np.uint32))
            t1 = time.perf_counter()
            handle = codec.reconstruct_batch_u32(survivors, targets, vols)
            t2 = time.perf_counter()
            _charge(busy, busy_lock, "stage_s", t1 - t0)
            _charge(busy, busy_lock, "device_s", t2 - t1)
            if not _q_put(write_q, (r, slot_id, buf3, handle), pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fds in out_fds:
                    for fd in fds.values():
                        try:
                            if durable and ok and not pipe.errors:
                                try:
                                    os.fsync(fd)
                                except OSError as e:
                                    if fsync_err is None:
                                        fsync_err = e
                            os.close(fd)
                        except OSError:
                            pass
                if not ok or pipe.errors or fsync_err is not None:
                    # abort contract: no partial rebuilt shard may
                    # survive for ANY volume in the chunk
                    for base in bases:
                        for tid in targets:
                            try:
                                os.remove(base + to_ext(tid))
                            except OSError:
                                pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    stats["pipeline_depth"] = depth
                    stats["ring_slots"] = ring.slots
                    stats["batch_volumes"] = b
                    stats["mesh"] = {"vol": vol_axis, "stripe": stripe}
                    if (
                        want_crcs
                        and ok
                        and not pipe.errors
                        and fsync_err is None
                    ):
                        stats["shard_crcs"] = _fold_rebuild_batch_crcs(
                            b, targets, step_of, round_crcs
                        )
                _trace_stages(_sp, busy)
                _sp.__exit__(*sys.exc_info())


# At or below this many (volume, tile) work items the host arm skips
# the thread pipeline entirely: on small batches every queue handoff
# and Thread.start costs a scheduler wakeup (milliseconds on a busy
# single-CPU host) that dwarfs the native-codec work it brokers.
_HOST_INLINE_TILES = 16


def _rebuild_batch_chunk_host_inline(
    bases: list[str], rs, rows, survivors: tuple[int, ...],
    targets: tuple[int, ...], sizes: list[int],
    items: list[tuple[int, int]], tile_bytes: int, stats, durable,
    want_crcs,
) -> None:
    """Zero-thread host arm for small batches: one staging buffer, one
    pass over the flat (volume, tile) work list, decode via the group's
    cached decode-rows matrix. Many-small-volumes repair is latency-
    bound on fixed costs, so the win here is paying ONE set of them for
    the whole batch and none of the pipeline's per-handoff scheduler
    wakeups. Same durability/abort contract as the threaded arms."""
    from seaweedfs_tpu.ec.ec_files import to_ext

    b = len(bases)
    busy = {"read_s": 0.0, "compute_s": 0.0, "write_s": 0.0}
    crc_parts: list[tuple[int, int, int, list[int]]] = []
    wall0 = time.perf_counter()
    buf = np.empty((DATA_SHARDS, tile_bytes), dtype=np.uint8)
    in_fds: list[list[int] | None] = [None] * b
    out_fds: list[dict[int, int]] = []
    read_local = EC_REPAIR_BYTES_READ.labels("local")
    ok = False
    with trace.span(
        "ec_stream.rebuild_batch",
        nbytes=sum(sizes) * max(1, len(targets)),
    ) as _sp:
        try:
            for v, base in enumerate(bases):
                fds: dict[int, int] = {}
                out_fds.append(fds)
                for tid in targets:
                    fds[tid] = os.open(
                        base + to_ext(tid),
                        os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                        0o644,
                    )
                    _preallocate(fds[tid], sizes[v])
            for v, off in items:
                vol_fds = in_fds[v]
                if vol_fds is None:
                    vol_fds = in_fds[v] = [
                        os.open(bases[v] + to_ext(s), os.O_RDONLY)
                        for s in survivors
                    ]
                step = min(tile_bytes, sizes[v] - off)
                tile = buf[:, :step]
                t0 = time.perf_counter()
                for j in range(DATA_SHARDS):
                    got = _pread_into(vol_fds[j], tile[j], off)
                    read_local.inc(got)
                    if got != step:
                        raise ValueError(
                            f"ec shard {survivors[j]} truncated: "
                            f"expected {step} at {off} "
                            f"({bases[v] + to_ext(survivors[j])})"
                        )
                t1 = time.perf_counter()
                rebuilt = rs._apply(rows, tile)
                if want_crcs:
                    from seaweedfs_tpu.util.crc import crc32c

                    crc_parts.append((v, off, step, [
                        crc32c(
                            np.ascontiguousarray(rebuilt[t]).tobytes()
                        )
                        for t in range(len(targets))
                    ]))
                t2 = time.perf_counter()
                for t, tid in enumerate(targets):
                    _pwrite_full(
                        out_fds[v][tid],
                        np.ascontiguousarray(rebuilt[t]),
                        off,
                    )
                    EC_REPAIR_BYTES_WRITTEN.inc(step)
                t3 = time.perf_counter()
                busy["read_s"] += t1 - t0
                busy["compute_s"] += t2 - t1
                busy["write_s"] += t3 - t2
            ok = True
        finally:
            for vol_fds in in_fds:
                for ifd in vol_fds or ():
                    try:
                        os.close(ifd)
                    except OSError:
                        pass
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fds in out_fds:
                    for fd in fds.values():
                        try:
                            if durable and ok:
                                try:
                                    os.fsync(fd)
                                except OSError as e:
                                    if fsync_err is None:
                                        fsync_err = e
                            os.close(fd)
                        except OSError:
                            pass
                if not ok or fsync_err is not None:
                    for base in bases:
                        for tid in targets:
                            try:
                                os.remove(base + to_ext(tid))
                            except OSError:
                                pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(stats, busy, wall0, 1, 1)
                    stats["batch_volumes"] = b
                    stats["codec_arm"] = "host"
                    stats["host_inline"] = True
                    if want_crcs and ok and fsync_err is None:
                        stats["shard_crcs"] = _fold_host_batch_crcs(
                            b, targets, crc_parts
                        )
                _trace_stages(_sp, busy)


def _rebuild_batch_chunk_host(
    bases: list[str], rs, survivors: tuple[int, ...],
    targets: tuple[int, ...], tile_bytes, stats, durable, want_crcs,
    reader_threads, writer_threads,
) -> None:
    """Host arm of the batch rebuild: one shared pipeline whose work
    items are per-(volume, tile) survivor gathers, decoded in the
    writer pool with the group's single cached decode-rows matrix.
    Slots stay at the single-volume driver's [k, tile] size (cache-
    resident on small hosts — an all-volumes-per-round slot measurably
    loses CPU to memory traffic), and the stream crosses volume
    boundaries without the per-volume spawn/drain the serial path
    pays. Same abort contract as the mesh arm."""
    from seaweedfs_tpu.ec.ec_files import to_ext

    tile_bytes = tile_bytes or DEFAULT_TILE_BYTES // 2
    writer_threads = writer_threads or DEFAULT_WRITER_THREADS
    reader_threads = reader_threads or DEFAULT_READER_THREADS
    depth = pipeline_depth()
    b = len(bases)
    sizes = [
        os.path.getsize(base + to_ext(survivors[0])) for base in bases
    ]
    # flat (volume, offset) work list: the pipeline streams straight
    # through volume boundaries, no drain between them
    items = [
        (v, off)
        for v in range(b)
        for off in range(0, sizes[v], tile_bytes)
    ]
    if not items:
        from seaweedfs_tpu.util import durable as _durable

        for base in bases:
            for t in targets:
                open(base + to_ext(t), "wb").close()
                if durable:
                    _durable.fsync_path(base + to_ext(t))
        if stats is not None:
            stats["batch_volumes"] = b
            stats["codec_arm"] = "host"
            if want_crcs:
                stats["shard_crcs"] = [
                    {t: 0 for t in targets} for _ in bases
                ]
        return

    rows = rs.decode_rows(tuple(survivors), tuple(targets))
    if len(items) <= _HOST_INLINE_TILES:
        return _rebuild_batch_chunk_host_inline(
            bases, rs, rows, survivors, targets, sizes, items,
            tile_bytes, stats, durable, want_crcs,
        )
    pipe = _Pipeline()
    read_q: queue.Queue = queue.Queue(maxsize=max(2, reader_threads))
    write_q: queue.Queue = queue.Queue(maxsize=depth)
    ring = _StagingRing(
        depth + writer_threads + 1, DATA_SHARDS * tile_bytes
    )
    busy = {
        "read_s": 0.0,
        "stage_s": 0.0,
        "device_s": 0.0,
        "writeback_s": 0.0,
        "compute_s": 0.0,
        "write_s": 0.0,
    }
    busy_lock = threading.Lock()
    # (volume, offset, step, [crc per target]); append is GIL-atomic,
    # order restored by sorting on offset at fold time
    crc_parts: list[tuple[int, int, int, list[int]]] = []
    wall0 = time.perf_counter()
    _sp = trace.span(
        "ec_stream.rebuild_batch",
        nbytes=sum(sizes) * max(1, len(targets)),
    )
    _sp.__enter__()

    idx_lock = threading.Lock()
    idx_iter = iter(items)
    out_fds: list[dict[int, int]] = []
    read_local = EC_REPAIR_BYTES_READ.labels("local")

    def reader():
        fds: dict[int, list[int]] = {}  # volume -> survivor fds, lazy
        try:
            while True:
                with idx_lock:
                    it = next(idx_iter, None)
                if it is None:
                    return
                v, off = it
                vol_fds = fds.get(v)
                if vol_fds is None:
                    vol_fds = fds[v] = [
                        os.open(bases[v] + to_ext(s), os.O_RDONLY)
                        for s in survivors
                    ]
                got_slot = ring.acquire(pipe.stop)
                if got_slot is None:
                    return
                slot_id, buf = got_slot
                step = min(tile_bytes, sizes[v] - off)
                t0 = time.perf_counter()
                tile = buf[: DATA_SHARDS * step].reshape(
                    DATA_SHARDS, step
                )
                for j in range(DATA_SHARDS):
                    got = _pread_into(vol_fds[j], tile[j], off)
                    read_local.inc(got)
                    if got != step:
                        raise ValueError(
                            f"ec shard {survivors[j]} truncated: "
                            f"expected {step} at {off} "
                            f"({bases[v] + to_ext(survivors[j])})"
                        )
                _charge(busy, busy_lock, "read_s", time.perf_counter() - t0)
                if not _q_put(
                    read_q, (v, off, step, slot_id, tile), pipe.stop
                ):
                    ring.release(slot_id)
                    return
        finally:
            for vol_fds in fds.values():
                for fd in vol_fds:
                    os.close(fd)

    def writer():
        while True:
            item = _q_get(write_q, pipe.stop)
            if item is _EOF or item is _STOPPED:
                return
            v, off, step, slot_id, tile = item
            t0 = time.perf_counter()
            rebuilt = rs._apply(rows, tile)
            t1 = time.perf_counter()
            if want_crcs:
                from seaweedfs_tpu.util.crc import crc32c

                crc_parts.append((v, off, step, [
                    crc32c(np.ascontiguousarray(rebuilt[t]).tobytes())
                    for t in range(len(targets))
                ]))
            t2 = time.perf_counter()
            for t, tid in enumerate(targets):
                _pwrite_full(
                    out_fds[v][tid],
                    np.ascontiguousarray(rebuilt[t]),
                    off,
                )
                EC_REPAIR_BYTES_WRITTEN.inc(step)
            t3 = time.perf_counter()
            ring.release(slot_id)
            _charge(busy, busy_lock, "compute_s", t2 - t0)
            _charge(busy, busy_lock, "write_s", t3 - t2)

    ok = False
    try:
        for v, base in enumerate(bases):
            fds: dict[int, int] = {}
            out_fds.append(fds)
            for tid in targets:
                fds[tid] = os.open(
                    base + to_ext(tid),
                    os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                    0o644,
                )
            for fd in fds.values():
                _preallocate(fd, sizes[v])
        for _ in range(min(reader_threads, len(items))):
            pipe.spawn(reader)
        for _ in range(writer_threads):
            pipe.spawn(writer)
        for _ in range(len(items)):
            item = _q_get(read_q, pipe.stop)
            if item is _STOPPED:
                break
            if not _q_put(write_q, item, pipe.stop):
                break
        for _ in range(writer_threads):
            if not _q_put(write_q, _EOF, pipe.stop):
                break
        ok = True
    finally:
        try:
            pipe.finish(caller_error=not ok)
        finally:
            tc0 = time.perf_counter()
            fsync_err: OSError | None = None
            try:
                for fds in out_fds:
                    for fd in fds.values():
                        try:
                            if durable and ok and not pipe.errors:
                                try:
                                    os.fsync(fd)
                                except OSError as e:
                                    if fsync_err is None:
                                        fsync_err = e
                            os.close(fd)
                        except OSError:
                            pass
                if not ok or pipe.errors or fsync_err is not None:
                    for base in bases:
                        for tid in targets:
                            try:
                                os.remove(base + to_ext(tid))
                            except OSError:
                                pass
                if fsync_err is not None:
                    raise fsync_err
            finally:
                busy["flush_s"] = time.perf_counter() - tc0
                if stats is not None:
                    _finish_stats(
                        stats, busy, wall0, reader_threads, writer_threads
                    )
                    stats["pipeline_depth"] = depth
                    stats["ring_slots"] = ring.slots
                    stats["batch_volumes"] = b
                    stats["codec_arm"] = "host"
                    if (
                        want_crcs
                        and ok
                        and not pipe.errors
                        and fsync_err is None
                    ):
                        stats["shard_crcs"] = _fold_host_batch_crcs(
                            b, targets, crc_parts
                        )
                _trace_stages(_sp, busy)
                _sp.__exit__(*sys.exc_info())


def _fold_host_batch_crcs(
    b: int, targets: tuple[int, ...],
    crc_parts: list[tuple[int, int, int, list[int]]],
) -> list[dict[int, int]]:
    """Per-volume {rebuilt shard id: whole-file CRC} folded from the
    writer pool's per-tile records in offset order."""
    from seaweedfs_tpu.util.crc import crc32c_combine

    out = [dict.fromkeys(targets, 0) for _ in range(b)]
    for v, off, step, crcs in sorted(crc_parts):
        for t, tid in enumerate(targets):
            out[v][tid] = crc32c_combine(out[v][tid], crcs[t], step)
    return out


def _fold_rebuild_batch_crcs(
    b: int,
    targets: tuple[int, ...],
    step_of: list[list[int]],
    round_crcs: list,
) -> list[dict[int, int]]:
    """Per-volume {rebuilt shard id: whole-file CRC} from the writer
    pool's per-round records, folded in round order."""
    from seaweedfs_tpu.util.crc import crc32c_combine

    out = []
    for v in range(b):
        acc = {tid: 0 for tid in targets}
        for r, vol_crcs in enumerate(round_crcs):
            step = step_of[r][v]
            if not step or vol_crcs is None or vol_crcs[v] is None:
                continue
            for t, tid in enumerate(targets):
                acc[tid] = crc32c_combine(acc[tid], vol_crcs[v][t], step)
        out.append(acc)
    return out
