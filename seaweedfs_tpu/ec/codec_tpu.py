"""TPU backend for the RS codec: GF(2^8) without a GF multiply unit.

Two device kernels, both byte-identical to the CPU LUT path:

1. **Bitsliced XOR-matmul** (the portable path). Multiplication by a
   constant c is GF(2)-linear on the 8 bits of a byte, so it is an 8x8
   bit-matrix B(c) with B(c)[i,j] = bit i of (c·2^j). A whole RS
   coefficient matrix M [R,C] expands to a bit-matrix A [R*8, C*8] of
   B-blocks, and ``parity_bits = (A @ data_bits) mod 2`` is an ordinary
   int8 matmul (accumulate in int32, then &1) on the MXU. Works on any
   backend, any shape.

2. **SWAR Horner Pallas kernel** (the fast path, TPU only). Each
   uint32 vector lane holds 4 byte-stream positions. For output row p,
   let u_j = XOR of inputs x[c] over columns c whose coefficient has
   bit j set; then y[p] = Horner(u_7..u_0) where each Horner step is a
   branchless SWAR GF-doubling ((y<<1 masked) ^ 0x1D on high-bit
   lanes). 8 u-terms + ≤7 doublings per output row, all VPU bitwise
   ops on VMEM-resident uint32 tiles — this is HBM-bandwidth-bound,
   ~180 GB/s payload on one v5e chip vs ~25 GB/s for the matmul path.

The same kernels serve encode (M = parity rows, the role of
`enc.Encode` at the reference's ec_encoder.go:173) and reconstruct
(M = rows of the inverted survivor matrix, store_ec.go:364; the 14x14
GF inversion stays host-side in gf256.py).

Everything is jittable, statically shaped, and usable under shard_map
over a Mesh for the batched multi-volume paths
(seaweedfs_tpu/parallel/ and __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec import register_backend


def gf_matrix_to_bits(matrix: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) coefficient matrix [R,C] to its GF(2) bit-matrix
    [R*8, C*8] of 8x8 blocks B(m[r,c])."""
    r, c = matrix.shape
    # mul_pow2[coef, j] = coef · 2^j in the field
    pow2 = (1 << np.arange(8)).astype(np.uint8)
    prods = gf256.MUL_TABLE[matrix.reshape(-1)[:, None], pow2[None, :]]  # [R*C, 8]
    # bits[i, (rc), j] = bit i of prods[(rc), j]
    bits = (prods[None, :, :] >> np.arange(8)[:, None, None]) & 1  # [8, R*C, 8]
    blocks = bits.transpose(1, 0, 2).reshape(r, c, 8, 8)  # [R, C, i, j]
    return (
        blocks.transpose(0, 2, 1, 3).reshape(r * 8, c * 8).astype(np.int8)
    )


def unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """[C, N] uint8 → [C*8, N] int8 bit-planes, LSB-first within a byte."""
    c, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, :, None]
    bits = (x[:, None, :] >> shifts) & jnp.uint8(1)
    return bits.reshape(c * 8, n).astype(jnp.int8)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """[R*8, N] int-ish bits → [R, N] uint8, LSB-first."""
    r8, n = bits.shape
    planes = bits.reshape(r8 // 8, 8, n).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(planes * weights, axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def apply_matrix_bits(a_bits: jnp.ndarray, inputs: jnp.ndarray) -> jnp.ndarray:
    """out[r] = XOR_c M[r,c]·inputs[c], via one int8 matmul on the MXU.

    a_bits: [R*8, C*8] int8 (from gf_matrix_to_bits)
    inputs: [C, N] uint8
    returns [R, N] uint8
    """
    x_bits = unpack_bits(inputs)  # [C*8, N] int8
    acc = jax.lax.dot_general(
        a_bits,
        x_bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [R*8, N] int32; each entry ≤ 80 so no overflow
    return pack_bits(acc & 1)


@functools.partial(jax.jit, static_argnames=())
def apply_matrix_bits_batch(a_bits: jnp.ndarray, inputs: jnp.ndarray) -> jnp.ndarray:
    """Batched variant: inputs [B, C, N] → [B, R, N] (vmapped matmul)."""
    return jax.vmap(lambda x: apply_matrix_bits(a_bits, x))(inputs)


# --- SWAR Horner Pallas kernel (fast path) ---------------------------------

# Lanes (uint32s) per grid block. 32768 lanes = 128 KiB of stream per
# input row; VMEM per block = (k + r) * tn * 4 B ≈ 1.8 MiB for RS(10,4).
# Swept on a v5e chip: 4K→82, 8K→89, 16K→95, 32K→100, 64K→101 GB/s
# sustained; 256K fails to compile (VMEM). 32K balances throughput
# against VMEM headroom for pipelining.
_SWAR_TN = 32768
# Minimum stream bytes for the Pallas path; below this the matmul path
# compiles faster and latency dominates anyway.
_SWAR_MIN_BYTES = 64 * 1024


def _swar_schedule(
    rows_tuple: tuple[int, ...], r_out: int, k: int, sched: bool = False
):
    """XOR schedules for one GF coefficient matrix: for output row p
    and bit j, sel[p][j] = the input columns whose coefficient has bit
    j set; maxj[p] = the highest set bit (Horner start).

    sched=True runs the Paar-style pair-CSE (ec/schedule.py) over the
    (p, j) sets: column pairs shared across sets are hoisted into
    temps, returned as `temps[t] = (a, b)` defining slot k+t as
    slot[a] ^ slot[b] (computed once per tile, shared by every output
    row instead of re-XORed per Horner term). Pure XOR reassociation —
    byte-identical output; WEED_EC_SCHEDULE=0 at the call sites
    restores the naive per-row sets."""
    rows = np.array(rows_tuple, dtype=np.uint8).reshape(r_out, k)
    sel = [
        [[c for c in range(k) if (rows[p, c] >> j) & 1] for j in range(8)]
        for p in range(r_out)
    ]
    maxj = [max((j for j in range(8) if sel[p][j]), default=0) for p in range(r_out)]
    temps: list[tuple[int, int]] = []
    if sched:
        from seaweedfs_tpu.ec.schedule import cse_pairs

        flat = [sel[p][j] for p in range(r_out) for j in range(8)]
        temps, new_flat = cse_pairs(flat, k)
        it = iter(new_flat)
        sel = [[list(next(it)) for _ in range(8)] for _ in range(r_out)]
    return sel, maxj, temps


def _swar_row(xs, sel_p, maxj_p):
    """One output row's SWAR Horner on uint32 lanes: y = Σ_j u_j · 2^j
    in GF(2^8), the GF doubling branchless on 4 packed bytes."""
    m_fe = jnp.uint32(0xFEFEFEFE)
    m_hb = jnp.uint32(0x80808080)
    red = jnp.uint32(0x1D)  # x^8 reduction polynomial tail (0x11D)

    def xor_set(cs):
        acc = xs[cs[0]]
        for c in cs[1:]:
            acc = acc ^ xs[c]
        return acc

    y = None
    for j in range(maxj_p, -1, -1):
        if y is not None:
            hb = y & m_hb
            y = ((y << 1) & m_fe) ^ ((hb >> 7) * red)
        if sel_p[j]:
            u = xor_set(sel_p[j])
            y = u if y is None else y ^ u
    return y if y is not None else jnp.zeros_like(xs[0])


def _make_swar_kernel(
    rows_tuple: tuple[int, ...],
    r_out: int,
    k: int,
    batched: bool = False,
    sched: bool = False,
):
    """Build the Pallas kernel body for one GF coefficient matrix.

    The matrix is baked into the kernel as XOR schedules (see
    _swar_schedule); each output row is one _swar_row Horner chain.
    sched=True shares pair-CSE temps across all rows' Horner terms.

    batched=True builds the body for refs with a leading batch-block
    dim of 1 (the grid walks volumes × stream tiles), so one
    pallas_call serves a whole [B, k, n32] volume batch without a
    host-side transpose into the flat [k, B*n32] layout.
    """
    sel, maxj, temps = _swar_schedule(rows_tuple, r_out, k, sched)
    lead = (0,) if batched else ()  # ref index prefix for the batch dim

    def kernel(x_ref, o_ref):
        slots = [x_ref[lead + (c, slice(None))] for c in range(k)]
        for a, b in temps:
            slots.append(slots[a] ^ slots[b])
        for p in range(r_out):
            o_ref[lead + (p, slice(None))] = _swar_row(slots, sel[p], maxj[p])

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("tn", "r_out", "k", "rows_tuple", "interpret", "sched"),
)
def swar_apply_u32(
    data_u32: jnp.ndarray,
    tn: int,
    r_out: int,
    k: int,
    rows_tuple: tuple[int, ...],
    interpret: bool = False,
    sched: bool = False,
) -> jnp.ndarray:
    """data [k, n32] uint32 (4 stream bytes per lane) → [r_out, n32].

    n32 must be a multiple of tn. interpret=True runs the Pallas
    interpreter (for correctness tests on CPU hosts). sched toggles
    the CSE'd XOR schedule (static, so the kill switch recompiles
    rather than silently reusing the other arm's program)."""
    n = data_u32.shape[1]
    return pl.pallas_call(
        _make_swar_kernel(rows_tuple, r_out, k, sched=sched),
        grid=(n // tn,),
        in_specs=[
            pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint32),
        interpret=interpret,
    )(data_u32)


@functools.partial(
    jax.jit,
    static_argnames=("tn", "r_out", "k", "rows_tuple", "interpret", "sched"),
)
def swar_apply_u32_batch(
    data_u32: jnp.ndarray,
    tn: int,
    r_out: int,
    k: int,
    rows_tuple: tuple[int, ...],
    interpret: bool = False,
    sched: bool = False,
) -> jnp.ndarray:
    """data [B, k, n32] uint32 → [B, r_out, n32] uint32 (one kernel,
    grid = volumes × stream tiles). n32 must be a multiple of tn."""
    b, _, n = data_u32.shape
    return pl.pallas_call(
        _make_swar_kernel(rows_tuple, r_out, k, batched=True, sched=sched),
        grid=(b, n // tn),
        in_specs=[
            pl.BlockSpec(
                (1, k, tn), lambda bi, i: (bi, 0, i), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec(
            (1, r_out, tn), lambda bi, i: (bi, 0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, r_out, n), jnp.uint32),
        interpret=interpret,
    )(data_u32)


def _make_swar_verify_kernel(
    rows_tuple: tuple[int, ...], r_out: int, k: int, sched: bool = False
):
    """Fused verify body: recompute each parity row's tile in VMEM
    (same _swar_row Horner chain as encode), compare against the given
    parity tile IN REGISTER, and accumulate the mismatched-lane count
    into a per-volume scalar. The recomputed parity never reaches HBM —
    that round-trip (write [B,r,N], re-read it plus the given parity
    for the != pass) is what ran the unfused verify at a third of the
    encode rate (VERDICT r4 weak #2).

    Grid is (volumes, stream tiles); the scalar output block is
    revisited across the tile dim (TPU grids run sequentially), so
    tile 0 initialises and later tiles accumulate."""
    sel, maxj, temps = _swar_schedule(rows_tuple, r_out, k, sched)

    def kernel(x_ref, p_ref, o_ref, acc_ref):
        slots = [x_ref[0, c, :] for c in range(k)]
        for a, b in temps:
            slots.append(slots[a] ^ slots[b])
        mism = None  # (tn,) int32: per-LANE mismatch count this tile
        for p in range(r_out):
            y = _swar_row(slots, sel[p], maxj[p])
            d = (y != p_ref[0, p, :]).astype(jnp.int32)
            mism = d if mism is None else mism + d

        # The reduction stays VECTORIZED until the last tile: lanewise
        # int32 adds into a VMEM scratch accumulator (persistent across
        # the sequential grid), with exactly ONE cross-lane fold per
        # volume at its final tile. Folding every tile's (tn,) vector
        # to a scalar in-kernel was measured at a third of the encode
        # rate — the cross-lane fold, not HBM traffic, was the cost.
        bi, i = pl.program_id(0), pl.program_id(1)
        nt = pl.num_programs(1)

        @pl.when(i == 0)
        def _init():
            acc_ref[...] = mism

        @pl.when(i != 0)
        def _acc():
            acc_ref[...] = acc_ref[...] + mism

        # o_ref is the whole [B, 1] SMEM output (Mosaic requires
        # scalar-output blocks to span the array); this volume's slot
        # is written once, at its last stream tile
        @pl.when(i == nt - 1)
        def _fold():
            o_ref[bi, 0] = jnp.sum(acc_ref[...])

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("tn", "r_out", "k", "rows_tuple", "interpret", "sched"),
)
def swar_verify_u32_batch(
    data_u32: jnp.ndarray,
    parity_u32: jnp.ndarray,
    tn: int,
    r_out: int,
    k: int,
    rows_tuple: tuple[int, ...],
    interpret: bool = False,
    sched: bool = False,
) -> jnp.ndarray:
    """data [B, k, n32] + parity [B, r_out, n32] uint32 → [B] int32
    mismatched-lane counts (0 = verified), without materialising the
    recomputed parity. n32 must be a multiple of tn."""
    b, _, n = data_u32.shape
    counts = pl.pallas_call(
        _make_swar_verify_kernel(rows_tuple, r_out, k, sched=sched),
        grid=(b, n // tn),
        in_specs=[
            pl.BlockSpec(
                (1, k, tn), lambda bi, i: (bi, 0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, r_out, tn), lambda bi, i: (bi, 0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (b, 1), lambda bi, i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((tn,), jnp.int32)],
        interpret=interpret,
    )(data_u32, parity_u32)
    return counts[:, 0]


def swar_verify_matrix_u32_batch(
    matrix: np.ndarray,
    data_u32: jnp.ndarray,
    parity_u32: jnp.ndarray,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused batched verify against one GF coefficient matrix (the
    parity rows): [B] int32 mismatched-lane counts."""
    from seaweedfs_tpu.ec.schedule import schedule_enabled

    rows_tuple = tuple(int(v) for v in np.asarray(matrix, dtype=np.uint8).reshape(-1))
    r_out, k = matrix.shape
    return swar_verify_u32_batch(
        data_u32,
        parity_u32,
        _swar_tn(data_u32.shape[2]),
        r_out,
        k,
        rows_tuple,
        interpret,
        sched=schedule_enabled(),
    )


def swar_apply_matrix_u32_batch(
    matrix: np.ndarray, inputs_u32: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Batched device-resident SWAR: [B, k, n32] uint32 → [B, R, n32].
    Same packing contract as swar_apply_matrix_u32."""
    from seaweedfs_tpu.ec.schedule import schedule_enabled

    rows_tuple = tuple(int(v) for v in np.asarray(matrix, dtype=np.uint8).reshape(-1))
    r_out, k = matrix.shape
    return swar_apply_u32_batch(
        inputs_u32,
        _swar_tn(inputs_u32.shape[2]),
        r_out,
        k,
        rows_tuple,
        interpret,
        sched=schedule_enabled(),
    )


def apply_matrix_bits_u32_batch(
    a_bits: jnp.ndarray, inputs_u32: jnp.ndarray
) -> jnp.ndarray:
    """Matmul path on u32-lane data: bitcast to bytes, apply, bitcast
    back — byte-identical to the SWAR path on the same lanes (the CPU
    fallback inside mesh shard_map programs)."""
    b, k, n32 = inputs_u32.shape
    u8 = jax.lax.bitcast_convert_type(inputs_u32, jnp.uint8).reshape(b, k, n32 * 4)
    out = apply_matrix_bits_batch(a_bits, u8)
    r = out.shape[1]
    return jax.lax.bitcast_convert_type(out.reshape(b, r, n32, 4), jnp.uint32)


def apply_matrix_bits_u32(
    a_bits: jnp.ndarray, inputs_u32: jnp.ndarray
) -> jnp.ndarray:
    """Single-tile variant of apply_matrix_bits_u32_batch: [k, n32]
    uint32 → [R, n32] uint32 (the non-TPU arm of the fused stream
    stage, where the SWAR Pallas kernel cannot lower)."""
    return apply_matrix_bits_u32_batch(a_bits, inputs_u32[None])[0]


def _swar_tn(n32: int) -> int:
    """Largest supported tile dividing n32 (n32 is a power of two ≥ 256
    on all SWAR call sites, so this always succeeds)."""
    tn = min(_SWAR_TN, n32)
    while n32 % tn:
        tn //= 2
    return tn


def _on_tpu() -> bool:
    """True only on a real TPU backend: the SWAR kernel lowers via
    Mosaic-TPU (pltpu.VMEM block specs), so on any other accelerator
    (GPU) the portable bit-matmul path must serve instead. Distinct
    from codec.default_backend()'s any-accelerator probe, which picks
    the *backend name*; this picks the kernel within it."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def swar_apply_matrix_u32(
    matrix: np.ndarray, inputs_u32: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Device-resident SWAR path on uint32 lanes.

    inputs_u32 [k, n32] is the byte stream viewed 4-bytes-per-lane
    (little-endian, i.e. numpy ``.view(np.uint32)``); n32 must be a
    multiple of 256. Returns [R, n32] uint32 holding the output bytes
    in the same packing. The coefficient matrix is baked into the
    kernel (compiled once per distinct matrix — parity rows plus one
    decode matrix per survivor set, all tiny counts in practice)."""
    from seaweedfs_tpu.ec.schedule import schedule_enabled

    rows_tuple = tuple(int(v) for v in np.asarray(matrix, dtype=np.uint8).reshape(-1))
    r_out, k = matrix.shape
    return swar_apply_u32(
        inputs_u32,
        _swar_tn(inputs_u32.shape[1]),
        r_out,
        k,
        rows_tuple,
        interpret,
        sched=schedule_enabled(),
    )


def swar_apply_matrix_host(
    matrix: np.ndarray, inputs: np.ndarray, interpret: bool = False
) -> np.ndarray:
    """Host-interop SWAR: numpy [k, N] uint8 in → [R, N] uint8 out.

    The u8↔u32 reinterpretation happens host-side (free view) — a
    device-side bitcast would materialize a 32x-padded copy under
    TPU (8,128) tiling."""
    u32 = np.ascontiguousarray(inputs).view(np.uint32)
    out = swar_apply_matrix_u32(matrix, jnp.asarray(u32), interpret)
    return np.asarray(jax.device_get(out)).view(np.uint8)


_BITS_CACHE: dict[bytes, jnp.ndarray] = {}


def _cached_bits(matrix: np.ndarray) -> jnp.ndarray:
    """Device-resident bit-matrix, memoized — streaming encode calls
    the backend once per IO batch with the same constant matrix."""
    key = matrix.tobytes() + bytes(matrix.shape)
    bits = _BITS_CACHE.get(key)
    if bits is None:
        bits = jnp.asarray(gf_matrix_to_bits(matrix))
        _BITS_CACHE[key] = bits
    return bits


def _bucket_len(n: int) -> int:
    """Round a byte-stream length up to a power of two (min 1 KiB).

    The serving path calls the codec with arbitrary needle-interval
    sizes; jit specializes per shape, so bucketing caps compilation at
    ~log2(max) variants instead of one per distinct request size."""
    return max(1024, 1 << (n - 1).bit_length())


def tpu_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """Host-interop backend for codec.ReedSolomon: numpy in, numpy out.

    Zero-pads the stream dim to a size bucket (GF math is positionwise,
    so padding never changes the first n output bytes). Large streams
    on an accelerator take the SWAR Pallas kernel; small/CPU ones the
    bit-matmul."""
    n = inputs.shape[1]
    nb = _bucket_len(n)
    if nb != n:
        padded = np.zeros((inputs.shape[0], nb), dtype=np.uint8)
        padded[:, :n] = inputs
        inputs = padded
    if nb >= _SWAR_MIN_BYTES and _on_tpu():
        return swar_apply_matrix_host(matrix, inputs)[:, :n]
    out = apply_matrix_bits(_cached_bits(matrix), jnp.asarray(inputs))
    return np.asarray(jax.device_get(out))[:, :n]


register_backend("tpu", tpu_apply_matrix)


class TpuCodecKernels:
    """Device-resident kernels for one RS(k,p) configuration.

    Holds the encode bit-matrix on device; decode bit-matrices are
    built host-side per survivor set (cached) and shipped once per
    rebuild. Used by the streaming encoder, bench.py and the graft
    entry points.
    """

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_code_matrix(data_shards, self.total_shards)
        self.encode_bits_host = gf_matrix_to_bits(self.matrix[data_shards:])
        self.encode_bits = jnp.asarray(self.encode_bits_host)
        self._decode_bits_cache: dict[tuple[int, ...], np.ndarray] = {}
        self._decode_rows_cache: dict[tuple[int, ...], np.ndarray] = {}

    def encode(self, data: jnp.ndarray) -> jnp.ndarray:
        """data [k, N] uint8 (device) → parity [p, N] uint8 (device)."""
        return apply_matrix_bits(self.encode_bits, data)

    def encode_u32(self, data_u32: jnp.ndarray) -> jnp.ndarray:
        """SWAR fast path: [k, n32] uint32 byte-stream view → parity
        [p, n32] uint32 (same packing). ~7x the matmul path's
        throughput on a v5e chip."""
        return swar_apply_matrix_u32(self.matrix[self.data_shards :], data_u32)

    def encode_batch(self, data: jnp.ndarray) -> jnp.ndarray:
        """data [B, k, N] → parity [B, p, N]."""
        return apply_matrix_bits_batch(self.encode_bits, data)

    def encode_u32_crc(
        self, data_u32: jnp.ndarray, interpret: bool = False
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Fused encode + Castagnoli pass: [k, n32] uint32 → (parity
        [p, n32], crcs [k+p] uint32 — standard CRC-32C of every shard
        row's bytes, data rows included). One jitted program: the CRC
        accumulation (ec/crc_kernel.py bit-matmuls) runs over the tile
        while it is still device-resident, so the host consumes
        (shard bytes, crc) pairs without a second pass over parity
        bytes. SWAR kernel on TPU (or under interpret), bit-matmul
        elsewhere — CRCs are bit-identical to util/crc.crc32c either
        way."""
        from seaweedfs_tpu.ec import crc_kernel

        if interpret or _on_tpu():
            parity = swar_apply_matrix_u32(
                self.matrix[self.data_shards :], data_u32, interpret
            )
        else:
            parity = apply_matrix_bits_u32(self.encode_bits, data_u32)
        crcs = crc_kernel.crc32c_rows(
            jnp.concatenate([data_u32, parity], axis=0)
        )
        return parity, crcs

    def reconstruct_u32_crc(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data_u32: jnp.ndarray,
        interpret: bool = False,
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Fused rebuild + Castagnoli pass: survivor tile [k, n32]
        uint32 → (rebuilt [len(targets), n32], crcs [len(targets)]
        uint32) in one program (see encode_u32_crc)."""
        from seaweedfs_tpu.ec import crc_kernel

        rows = self.decode_rows_for(survivors, targets)
        if interpret or _on_tpu():
            rebuilt = swar_apply_matrix_u32(rows, shard_data_u32, interpret)
        else:
            rebuilt = apply_matrix_bits_u32(
                jnp.asarray(self.decode_bits_for(survivors, targets)),
                shard_data_u32,
            )
        return rebuilt, crc_kernel.crc32c_rows(rebuilt)

    def decode_rows_for(
        self, survivors: tuple[int, ...], targets: tuple[int, ...]
    ) -> np.ndarray:
        """GF coefficient rows mapping k survivor shards → targets.

        survivors: k shard ids present (sorted); targets: shard ids to
        produce. Data targets come from the inverted survivor submatrix;
        parity targets from (parity rows · inverse).
        """
        key = survivors + (256,) + targets
        cached = self._decode_rows_cache.get(key)
        if cached is not None:
            return cached
        stacked = gf256.decode_rows(self.matrix, survivors, targets)
        self._decode_rows_cache[key] = stacked
        return stacked

    def decode_bits_for(
        self, survivors: tuple[int, ...], targets: tuple[int, ...]
    ) -> np.ndarray:
        """Bit-matrix form of decode_rows_for (for the matmul path)."""
        key = survivors + (256,) + targets
        cached = self._decode_bits_cache.get(key)
        if cached is None:
            cached = gf_matrix_to_bits(self.decode_rows_for(survivors, targets))
            self._decode_bits_cache[key] = cached
        return cached

    def reconstruct(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data: jnp.ndarray,
    ) -> jnp.ndarray:
        """shard_data [k, N] uint8 = survivor shards (in `survivors`
        order) → [len(targets), N] rebuilt shards."""
        bits = jnp.asarray(self.decode_bits_for(survivors, targets))
        return apply_matrix_bits(bits, shard_data)

    def reconstruct_u32(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data_u32: jnp.ndarray,
    ) -> jnp.ndarray:
        """SWAR fast path: survivor shards as [k, n32] uint32 views →
        [len(targets), n32] rebuilt shards (same packing)."""
        rows = self.decode_rows_for(survivors, targets)
        return swar_apply_matrix_u32(rows, shard_data_u32)
