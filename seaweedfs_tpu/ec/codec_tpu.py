"""TPU backend for the RS codec: GF(2^8) as bitsliced XOR-matmuls.

TPUs have no native GF(2^8) multiply. The trick (SURVEY.md §7 step 2):
multiplication by a constant c is GF(2)-linear on the 8 bits of a byte,
so it is an 8x8 bit-matrix B(c) with B(c)[i,j] = bit i of (c·2^j).
A whole RS coefficient matrix M [R,C] expands to a bit-matrix
A [R*8, C*8] of B-blocks, and

    parity_bits = (A @ data_bits) mod 2

is an ordinary int8 matmul (accumulate in int32, then &1) — exactly the
shape of work the MXU is built for. Contraction dim C*8=80 and output
R*8=32 for RS(10,4); the N (byte-stream) dimension is the wide one.

The same kernel serves encode (A = parity rows) and reconstruct
(A = rows of the inverted survivor matrix, computed host-side in
gf256.py — a 14x14 inversion is not TPU work).

Everything is jittable, statically shaped, and usable under shard_map
over a Mesh for the batched multi-volume paths (parallel/ and
__graft_entry__.dryrun_multichip exercise that).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec import register_backend


def gf_matrix_to_bits(matrix: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) coefficient matrix [R,C] to its GF(2) bit-matrix
    [R*8, C*8] of 8x8 blocks B(m[r,c])."""
    r, c = matrix.shape
    # mul_pow2[coef, j] = coef · 2^j in the field
    pow2 = (1 << np.arange(8)).astype(np.uint8)
    prods = gf256.MUL_TABLE[matrix.reshape(-1)[:, None], pow2[None, :]]  # [R*C, 8]
    # bits[i, (rc), j] = bit i of prods[(rc), j]
    bits = (prods[None, :, :] >> np.arange(8)[:, None, None]) & 1  # [8, R*C, 8]
    blocks = bits.transpose(1, 0, 2).reshape(r, c, 8, 8)  # [R, C, i, j]
    return (
        blocks.transpose(0, 2, 1, 3).reshape(r * 8, c * 8).astype(np.int8)
    )


def unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """[C, N] uint8 → [C*8, N] int8 bit-planes, LSB-first within a byte."""
    c, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, :, None]
    bits = (x[:, None, :] >> shifts) & jnp.uint8(1)
    return bits.reshape(c * 8, n).astype(jnp.int8)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """[R*8, N] int-ish bits → [R, N] uint8, LSB-first."""
    r8, n = bits.shape
    planes = bits.reshape(r8 // 8, 8, n).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(planes * weights, axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def apply_matrix_bits(a_bits: jnp.ndarray, inputs: jnp.ndarray) -> jnp.ndarray:
    """out[r] = XOR_c M[r,c]·inputs[c], via one int8 matmul on the MXU.

    a_bits: [R*8, C*8] int8 (from gf_matrix_to_bits)
    inputs: [C, N] uint8
    returns [R, N] uint8
    """
    x_bits = unpack_bits(inputs)  # [C*8, N] int8
    acc = jax.lax.dot_general(
        a_bits,
        x_bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [R*8, N] int32; each entry ≤ 80 so no overflow
    return pack_bits(acc & 1)


@functools.partial(jax.jit, static_argnames=())
def apply_matrix_bits_batch(a_bits: jnp.ndarray, inputs: jnp.ndarray) -> jnp.ndarray:
    """Batched variant: inputs [B, C, N] → [B, R, N] (vmapped matmul)."""
    return jax.vmap(lambda x: apply_matrix_bits(a_bits, x))(inputs)


_BITS_CACHE: dict[bytes, jnp.ndarray] = {}


def _cached_bits(matrix: np.ndarray) -> jnp.ndarray:
    """Device-resident bit-matrix, memoized — streaming encode calls
    the backend once per IO batch with the same constant matrix."""
    key = matrix.tobytes() + bytes(matrix.shape)
    bits = _BITS_CACHE.get(key)
    if bits is None:
        bits = jnp.asarray(gf_matrix_to_bits(matrix))
        _BITS_CACHE[key] = bits
    return bits


def _bucket_len(n: int) -> int:
    """Round a byte-stream length up to a power of two (min 1 KiB).

    The serving path calls the codec with arbitrary needle-interval
    sizes; jit specializes per shape, so bucketing caps compilation at
    ~log2(max) variants instead of one per distinct request size."""
    return max(1024, 1 << (n - 1).bit_length())


def tpu_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """Host-interop backend for codec.ReedSolomon: numpy in, numpy out.

    Zero-pads the stream dim to a size bucket (GF math is positionwise,
    so padding never changes the first n output bytes)."""
    n = inputs.shape[1]
    nb = _bucket_len(n)
    if nb != n:
        padded = np.zeros((inputs.shape[0], nb), dtype=np.uint8)
        padded[:, :n] = inputs
        inputs = padded
    out = apply_matrix_bits(_cached_bits(matrix), jnp.asarray(inputs))
    return np.asarray(jax.device_get(out))[:, :n]


register_backend("tpu", tpu_apply_matrix)


class TpuCodecKernels:
    """Device-resident kernels for one RS(k,p) configuration.

    Holds the encode bit-matrix on device; decode bit-matrices are
    built host-side per survivor set (cached) and shipped once per
    rebuild. Used by the streaming encoder, bench.py and the graft
    entry points.
    """

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_code_matrix(data_shards, self.total_shards)
        self.encode_bits_host = gf_matrix_to_bits(self.matrix[data_shards:])
        self.encode_bits = jnp.asarray(self.encode_bits_host)
        self._decode_bits_cache: dict[tuple[int, ...], np.ndarray] = {}

    def encode(self, data: jnp.ndarray) -> jnp.ndarray:
        """data [k, N] uint8 (device) → parity [p, N] uint8 (device)."""
        return apply_matrix_bits(self.encode_bits, data)

    def encode_batch(self, data: jnp.ndarray) -> jnp.ndarray:
        """data [B, k, N] → parity [B, p, N]."""
        return apply_matrix_bits_batch(self.encode_bits, data)

    def decode_bits_for(
        self, survivors: tuple[int, ...], targets: tuple[int, ...]
    ) -> np.ndarray:
        """Bit-matrix mapping k survivor shards → the target shards.

        survivors: k shard ids present (sorted); targets: shard ids to
        produce. Data targets come from the inverted survivor submatrix;
        parity targets from (parity rows · inverse).
        """
        key = survivors + (256,) + targets
        cached = self._decode_bits_cache.get(key)
        if cached is not None:
            return cached
        k = self.data_shards
        sub = gf256.sub_matrix_for_survivors(self.matrix, list(survivors))
        inv = gf256.mat_inv(sub)  # [k, k]: survivors → data shards
        rows = []
        for t in targets:
            if t < k:
                rows.append(inv[t])
            else:
                # parity row in terms of data, composed with inv
                rows.append(gf256.mat_mul(self.matrix[t : t + 1], inv)[0])
        bits = gf_matrix_to_bits(np.stack(rows))
        self._decode_bits_cache[key] = bits
        return bits

    def reconstruct(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data: jnp.ndarray,
    ) -> jnp.ndarray:
        """shard_data [k, N] uint8 = survivor shards (in `survivors`
        order) → [len(targets), N] rebuilt shards."""
        bits = jnp.asarray(self.decode_bits_for(survivors, targets))
        return apply_matrix_bits(bits, shard_data)
