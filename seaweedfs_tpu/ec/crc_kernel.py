"""Device-side CRC32-C over u32-lane shard streams.

The streaming encode pipeline produces parity tiles ON DEVICE; the
host used to fetch the bytes and run the table CRC over them again
before the writer pool could stamp checksums — a second full pass over
every parity byte. This module folds the Castagnoli accumulation into
the same jitted program as the codec kernel, so a dispatch returns
(parity, per-row CRC) and the host never re-touches the bytes.

The trick is the same GF(2)-linearity the bitsliced codec kernels
lean on: with the init/final-xor constants stripped, a CRC register is
a linear function of the message bits, so

  * the raw CRC of each uint32 LANE (4 stream bytes) is one
    [N,32]x[32,32] bit-matmul against a constant lane matrix;
  * adjacent chunks combine with `crc(A||B) = Z_|B|(crc(A)) ^ crc(B)`
    where Z_k (the k-zero-byte register transit, util/crc) is another
    [32,32] bit-matrix — log2(lanes) halving rounds reduce a whole row
    to one register;
  * the init/final-xor constants re-enter as a single per-length XOR.

Everything is ordinary XLA (int8 matmul + bit packing, the
apply_matrix_bits idiom) — no Pallas, so it lowers on CPU and TPU with
bit-identical results to util/crc.crc32c, which the tests and the
bench --check pipeline-identity smoke enforce.

Shape contract: lane counts must be a power of two (every stream tile
the drivers dispatch is; odd tails fall back to the host table CRC in
the driver).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.util import crc as _crc


def crc_supported(nbytes: int) -> bool:
    """True when the device kernel serves a row of `nbytes` stream
    bytes: whole u32 lanes, power-of-two lane count."""
    if nbytes <= 0 or nbytes % 4:
        return False
    n32 = nbytes // 4
    return n32 & (n32 - 1) == 0


def _raw_transit(data: bytes, reg: int) -> int:
    """CRC register after processing `data` starting from `reg` (the
    init/final-xor constants of crc32c stripped off)."""
    return _crc.crc32c(data, reg ^ 0xFFFFFFFF) ^ 0xFFFFFFFF


@functools.lru_cache(maxsize=1)
def _lane_cols() -> tuple[int, ...]:
    """Columns of the lane operator: raw CRC of the 4-byte
    little-endian message holding lane bit b (the numpy
    ``.view(np.uint32)`` packing the SWAR kernels use)."""
    return tuple(
        _raw_transit((1 << b).to_bytes(4, "little"), 0) for b in range(32)
    )


def _bitmat(cols) -> np.ndarray:
    """32-column operator -> [32(in), 32(out)] int8 bit-matrix for the
    device-side matmul apply."""
    m = np.zeros((32, 32), dtype=np.int8)
    for b, c in enumerate(cols):
        for j in range(32):
            m[b, j] = (c >> j) & 1
    return m


@functools.lru_cache(maxsize=128)
def _shift_bitmat(nbytes: int) -> np.ndarray:
    """Bit-matrix of Z_nbytes (advance a raw CRC past nbytes zero
    bytes), host-built by operator squaring."""
    return _bitmat(_crc._zero_shift_cols(nbytes))


@functools.lru_cache(maxsize=128)
def _final_const(nbytes: int) -> int:
    """crc32c(M) = crc_raw0(M) ^ _final_const(len(M)): the init state
    pushed through the message length, plus the final xor."""
    return _crc._gf2_apply(
        _crc._zero_shift_cols(nbytes), 0xFFFFFFFF
    ) ^ 0xFFFFFFFF if nbytes else 0


_BIT_IDX = np.arange(32, dtype=np.uint32)


def _apply_bits(x: jnp.ndarray, m_bits: jnp.ndarray) -> jnp.ndarray:
    """Apply a [32,32] bit-matrix operator to every uint32 in x
    (elementwise over leading dims): unpack, int8 matmul, repack."""
    shifts = jnp.asarray(_BIT_IDX)
    bits = ((x[..., None] >> shifts) & jnp.uint32(1)).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bits,
        m_bits,
        (((bits.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return jnp.sum((acc & 1).astype(jnp.uint32) << shifts, axis=-1)


def crc_lin_rows(x_u32: jnp.ndarray) -> jnp.ndarray:
    """[..., n32] uint32 lanes -> [...] uint32 RAW (zero-init, no final
    xor) CRC of each row's 4*n32 bytes. The linear form — what crosses
    mesh devices, because raw CRCs of stream segments compose with the
    Z shift alone (mesh_codec's stripe-axis fold)."""
    n32 = x_u32.shape[-1]
    if n32 & (n32 - 1):
        raise ValueError(f"lane count {n32} is not a power of two")
    c = _apply_bits(x_u32, jnp.asarray(_bitmat(_lane_cols())))
    span = 4  # bytes covered by each element of c
    while c.shape[-1] > 1:
        m = jnp.asarray(_shift_bitmat(span))
        c = _apply_bits(c[..., 0::2], m) ^ c[..., 1::2]
        span *= 2
    return c[..., 0]


def finalize_rows(lin: jnp.ndarray, nbytes: int) -> jnp.ndarray:
    """Raw row CRCs -> standard crc32c values for rows of `nbytes`."""
    return lin ^ jnp.uint32(_final_const(nbytes))


def crc32c_rows(x_u32: jnp.ndarray) -> jnp.ndarray:
    """[..., n32] uint32 lanes -> [...] uint32 standard CRC-32C of each
    row's bytes — bit-identical to util/crc.crc32c on the same bytes."""
    return finalize_rows(crc_lin_rows(x_u32), x_u32.shape[-1] * 4)
