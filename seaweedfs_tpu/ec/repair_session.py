"""Rebuild sessions: piggyback degraded serving onto in-progress repair.

A rebuild of a lost shard decodes every byte of it from k survivors —
~k× the rebuilt bytes over the network (the Facebook warehouse study,
arXiv:1309.0186, measures exactly this k-gather as the #1 cluster
network tax). Meanwhile every *degraded GET* of the same volume is
independently gathering and decoding tiles of the very shard the
rebuild is regenerating, duplicating its reads byte for byte.

A RebuildSession joins the two planes on the rebuilding node:

  * the rebuild verb opens a session naming its target shards before
    the stream driver starts and closes it after;
  * degraded reads `donate()` every tile they reconstruct (and the
    session drains the volume's reconstructed-tile cache at open, so
    serving traffic that already ran counts too);
  * the driver's reader pool calls `consume()` per rebuild tile and
    fetches survivors only for the *gaps* donations don't cover —
    range-aligned sub-shard reads (arXiv:2205.11015's partial-repair
    observation: transfer only the bytes the decode actually needs);
  * `yield_to_serving()` between tiles keeps an active rebuild from
    starving live degraded GETs of the gather bandwidth they share —
    the serve-plane-first arbitration the RepairScheduler relies on
    (its repair verbs all drive this driver).

Sessions are process-local: piggyback pays when degraded traffic lands
on the rebuilding node (common — the scheduler rebuilds on a surviving
holder, which serves reads for the shards it holds). Cross-node
donation would ship the tiles it saves; deliberately out of scope.
"""

from __future__ import annotations

import threading
import time

from seaweedfs_tpu.stats.metrics import EC_REPAIR_DONATED_BYTES

_SESSIONS: dict[int, "RebuildSession"] = {}
_SESSIONS_LOCK = threading.Lock()

# donations kept at most this long per session before the cap drops new
# ones — a bound, not a budget: a shard is at most a few GB and serve
# tiles are 256 KiB, but a hot degraded workload could otherwise donate
# faster than the writer drains
_DONATION_CAP_BYTES = 64 << 20


class RebuildSession:
    def __init__(self, volume_id: int, targets: tuple[int, ...]):
        self.volume_id = volume_id
        self.targets = tuple(sorted(targets))
        self._lock = threading.Lock()
        # per-target shard: tile_off -> bytes (serve-tile granularity)
        self._donated: dict[int, dict[int, bytes]] = {
            t: {} for t in self.targets
        }
        self._bytes = 0
        # ranges the driver already claimed: late donations for them
        # are dropped (the decode already ran; bytes are identical)
        self._claimed: list[tuple[int, int]] = []
        self._serving = 0
        self._serving_cv = threading.Condition(self._lock)
        self.donated_bytes = 0  # accepted via donate()
        self.used_donated_bytes = 0  # actually consumed by the driver
        self.yields = 0  # times the reader pool paused for serving

    # -- serving side ------------------------------------------------------
    def donate(self, shard_id: int, offset: int, data: bytes) -> bool:
        """Hand a reconstructed tile to the rebuild. True when (some of)
        it was accepted: target shard, cap not exceeded, and at least
        part of the range still pending — a donation overlapping an
        already-claimed rebuild tile is TRIMMED to its unclaimed
        remainder, not rejected (serve tiles and rebuild tiles need not
        agree on size)."""
        if shard_id not in self._donated or not data:
            return False
        with self._lock:
            lo, hi = offset, offset + len(data)
            for c_off, c_len in self._claimed:
                if lo < c_off + c_len and c_off < hi:
                    if c_off <= lo and hi <= c_off + c_len:
                        return False  # fully claimed already
                    if c_off <= lo:
                        lo = c_off + c_len  # head claimed: keep tail
                    else:
                        hi = c_off  # tail (or middle) claimed: keep head
            if hi <= lo:
                return False
            data = data[lo - offset : hi - offset]
            offset = lo
            per = self._donated[shard_id]
            old = per.get(offset)
            if old is not None:
                return True  # already have these exact bytes
            if self._bytes + len(data) > _DONATION_CAP_BYTES:
                return False
            per[offset] = data
            self._bytes += len(data)
            self.donated_bytes += len(data)
            EC_REPAIR_DONATED_BYTES.inc(len(data))
            return True

    def serving_enter(self) -> None:
        with self._lock:
            self._serving += 1

    def serving_exit(self) -> None:
        with self._serving_cv:
            self._serving -= 1
            if self._serving <= 0:
                self._serving_cv.notify_all()

    # -- rebuild-driver side ----------------------------------------------
    def yield_to_serving(self, max_wait_s: float = 1.0) -> None:
        """Pause (bounded) while degraded gathers are in flight: repair
        is background work; a GET decoding right now owns the disks and
        the rack links first."""
        deadline = time.monotonic() + max_wait_s
        with self._serving_cv:
            waited = False
            while self._serving > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                waited = True
                self._serving_cv.wait(min(left, 0.05))
            if waited:
                # one pause = one yield, however many wait slices it
                # took (per-slice counting inflated the stat ~20x)
                self.yields += 1

    def consume(
        self, offset: int, step: int
    ) -> tuple[list[tuple[int, dict[int, bytes]]], list[tuple[int, int]]]:
        """Split the rebuild tile [offset, offset+step) against the
        donations: returns (covered, gaps). `covered` entries are
        (sub_off, {target: bytes}) where EVERY target shard has donated
        bytes for the whole subrange; `gaps` are (sub_off, sub_len)
        ranges the driver must still gather survivors for. The claimed
        range rejects late donations; consumed donations are freed."""
        end = offset + step
        with self._lock:
            self._claimed.append((offset, step))
            # coverage = intersection across targets of donated ranges
            pieces: dict[int, dict[int, bytes]] = {}
            for t in self.targets:
                per = self._donated[t]
                for d_off in list(per):
                    data = per[d_off]
                    if d_off >= end or d_off + len(data) <= offset:
                        continue
                    # clip the donation to the tile
                    lo = max(d_off, offset)
                    hi = min(d_off + len(data), end)
                    pieces.setdefault(lo, {})
                    if pieces[lo].get(t) is None:
                        pieces[lo][t] = data[lo - d_off : hi - d_off]
                    # free the consumed span but KEEP the out-of-window
                    # remainders: a serve tile bigger than the rebuild
                    # tile would otherwise lose most of its bytes to
                    # the first claim and the gather would re-fetch
                    # ranges that were already donated
                    per.pop(d_off)
                    self._bytes -= len(data)
                    if d_off < offset:
                        head = data[: offset - d_off]
                        per[d_off] = head
                        self._bytes += len(head)
                    if d_off + len(data) > end:
                        tail = data[end - d_off :]
                        per[end] = tail
                        self._bytes += len(tail)
            covered: list[tuple[int, dict[int, bytes]]] = []
            for lo in sorted(pieces):
                per_t = pieces[lo]
                if len(per_t) != len(self.targets):
                    continue  # some target lacks this range: still a gap
                lens = {len(b) for b in per_t.values()}
                if len(lens) != 1:
                    # ragged donations: keep the common prefix
                    n = min(lens)
                    per_t = {t: b[:n] for t, b in per_t.items()}
                covered.append((lo, per_t))
        # merge overlaps defensively and compute the gaps
        covered.sort()
        pruned: list[tuple[int, dict[int, bytes]]] = []
        cursor = offset
        gaps: list[tuple[int, int]] = []
        for lo, per_t in covered:
            n = len(next(iter(per_t.values())))
            if lo < cursor:  # overlap with the previous piece: clip
                cut = cursor - lo
                if cut >= n:
                    continue
                per_t = {t: b[cut:] for t, b in per_t.items()}
                lo, n = cursor, n - cut
            if lo > cursor:
                gaps.append((cursor, lo - cursor))
            pruned.append((lo, per_t))
            cursor = lo + n
        if cursor < end:
            gaps.append((cursor, end - cursor))
        # charge AFTER pruning: clipped/dropped pieces must not inflate
        # the piggyback-savings number the rebuild bench reports
        used = sum(
            len(b) for _off, per_t in pruned for b in per_t.values()
        )
        if used:
            with self._lock:
                self.used_donated_bytes += used
        return pruned, gaps


def open_session(volume_id: int, targets) -> RebuildSession:
    sess = RebuildSession(volume_id, tuple(targets))
    with _SESSIONS_LOCK:
        _SESSIONS[volume_id] = sess
    return sess


def close_session(sess: RebuildSession) -> None:
    with _SESSIONS_LOCK:
        if _SESSIONS.get(sess.volume_id) is sess:
            _SESSIONS.pop(sess.volume_id, None)


def find(volume_id: int) -> RebuildSession | None:
    with _SESSIONS_LOCK:
        return _SESSIONS.get(volume_id)
