""""native" EC codec backend: the SIMD C shim (native/gf256.c).

Registers on import, mirroring codec_tpu.py's pattern. This is the
counterpart of the reference's klauspost/reedsolomon AVX2 path
(ec_encoder.go:13) for hosts without an attached TPU — byte-identical
to the "cpu" numpy backend (tests/test_ec_codec.py cross-checks), just
~2 orders of magnitude faster, which makes end-to-end `ec.encode` of
real volume files disk-bound instead of codec-bound.

Importing this module raises ImportError when the shim can't build;
codec.default_backend() catches that and picks "cpu".
"""

from seaweedfs_tpu.ec.codec import register_backend
from seaweedfs_tpu.native.gf import apply_matrix as native_apply_matrix

register_backend("native", native_apply_matrix)
