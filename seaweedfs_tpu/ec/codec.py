"""Reed-Solomon codec: the `reedsolomon.Encoder` capability surface.

Mirrors the semantics the reference relies on (ec_encoder.go:173
`enc.Encode`, store_ec.go:364 `enc.ReconstructData`, rebuild loop
`enc.Reconstruct` at ec_encoder.go:227-281):

  encode(shards)            fill parity shards k..n-1 from data 0..k-1
  reconstruct(shards)       rebuild ALL missing shards (None entries)
  reconstruct_data(shards)  rebuild only missing DATA shards
  verify(shards)            recompute parity, compare

Shards are equal-length 1-D uint8 numpy arrays (missing = None). The
byte math runs through a pluggable backend:

  "cpu"     numpy LUT-gather XOR loops — bit-exact reference
  "native"  SIMD C shim (native/gf256.c, PSHUFB nibble tables) — the
            klauspost/reedsolomon-AVX2 role for plain hosts
  "tpu"     JAX SWAR/bitsliced kernels (codec_tpu.py)

All produce byte-identical output (tested against each other and
against the code-matrix algebra in gf256.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from seaweedfs_tpu.ec import gf256

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS

# backend name -> apply_matrix(matrix [R,C] u8, inputs [C,N] u8) -> [R,N] u8
_BACKENDS: dict[str, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {}


def register_backend(
    name: str, fn: Callable[[np.ndarray, np.ndarray], np.ndarray]
) -> None:
    _BACKENDS[name] = fn


def cpu_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """out[r] = XOR_c MUL[m[r,c]]·inputs[c] — vectorized LUT gathers."""
    r, c = matrix.shape
    assert inputs.shape[0] == c
    out = np.zeros((r, inputs.shape[1]), dtype=np.uint8)
    for ci in range(c):
        col = inputs[ci]
        for ri in range(r):
            coef = matrix[ri, ci]
            if coef == 0:
                continue
            if coef == 1:
                out[ri] ^= col
            else:
                out[ri] ^= gf256.MUL_TABLE[coef][col]
    return out


register_backend("cpu", cpu_apply_matrix)


# --- default-backend selection (the `ec.codec` config key) -----------------
#
# An explicit backend= argument always wins (servers thread their
# -ec.codec flag down through Store → DiskLocation → EcVolume). When no
# backend is given, the WEED_EC_CODEC env var (viper idiom for
# `ec.codec`) decides; otherwise auto-detect: tpu when an accelerator
# device is actually attached, else the native SIMD shim when it
# builds, else numpy (which beats XLA-on-CPU for this workload). All
# backends are byte-identical; selection is purely a performance
# choice, so a process-wide cached default is safe.

_default_backend = ""  # "" = undecided; resolved lazily
_LAZY_BACKENDS = ("tpu", "native")  # registered on first resolve


def default_backend() -> str:
    global _default_backend
    import os

    env = os.environ.get("WEED_EC_CODEC", "").strip().lower()
    if env:
        if env not in _LAZY_BACKENDS and env not in _BACKENDS:
            raise ValueError(
                f"WEED_EC_CODEC={env!r} is not a known EC backend "
                f"(expected one of: cpu, native, tpu)"
            )
        return env
    if not _default_backend:
        try:
            import jax

            has_accel = any(d.platform != "cpu" for d in jax.devices())
            _default_backend = "tpu" if has_accel else ""
        except Exception:
            pass
        if not _default_backend:
            try:
                from seaweedfs_tpu.ec import codec_native  # noqa: F401

                _default_backend = "native"
            except ImportError:
                _default_backend = "cpu"
    return _default_backend


class ReedSolomon:
    """Systematic RS(k, p) codec over GF(2^8), reference-field-compatible."""

    def __init__(
        self,
        data_shards: int = DATA_SHARDS,
        parity_shards: int = PARITY_SHARDS,
        backend: str | None = None,
    ):
        backend = backend or default_backend()
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("too many shards for GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_code_matrix(data_shards, self.total_shards)
        self.parity_rows = self.matrix[data_shards:].copy()
        self._backend_name = backend
        self._apply = self._resolve_backend(backend)
        # schedule optimization (ec/schedule.py): the numpy backend's
        # naive per-entry LUT chain is replaced by a precompiled
        # coefficient-grouped + pair-CSE'd XOR/mul program, compiled
        # here per (k,m) and reused by encode, rebuild and degraded
        # decode (they all route through self._apply). Byte-identical;
        # WEED_EC_SCHEDULE=0 is the kill switch restoring the naive
        # chain. The native/tpu backends keep their own realizations
        # (the SWAR kernel builder runs the same CSE pass device-side).
        from seaweedfs_tpu.ec import schedule as _schedule

        self.scheduled = backend == "cpu" and _schedule.schedule_enabled()
        if self.scheduled:
            self._apply = _schedule.scheduled_apply_matrix
            _schedule.compile_schedule(self.parity_rows)
        # cache: survivor-row tuple -> decode matrix (invert is host-side
        # 14x14 work; reuse across blocks of a streaming rebuild)
        self._decode_cache: dict[tuple[int, ...], np.ndarray] = {}
        # cache: (survivors, targets) -> decode ROWS — the per-target
        # slice every caller of gf256.decode_rows wants; one home so
        # the degraded read path and the stream rebuild driver don't
        # each grow their own (GIL-atomic dict ops; a racing recompute
        # is benign and identical)
        self._decode_rows_cache: dict[tuple, np.ndarray] = {}

    @staticmethod
    def _resolve_backend(name: str) -> Callable[[np.ndarray, np.ndarray], np.ndarray]:
        if name == "tpu" and "tpu" not in _BACKENDS:
            # lazy import so CPU-only users never touch jax
            from seaweedfs_tpu.ec import codec_tpu  # noqa: F401
        if name == "native" and "native" not in _BACKENDS:
            from seaweedfs_tpu.ec import codec_native  # noqa: F401
        try:
            return _BACKENDS[name]
        except KeyError:
            raise ValueError(
                f"unknown EC backend {name!r}; registered: {sorted(_BACKENDS)}"
            ) from None

    # --- helpers ---
    def _check_shards(
        self, shards: Sequence[Optional[np.ndarray]], allow_missing: bool
    ) -> int:
        if len(shards) != self.total_shards:
            raise ValueError(
                f"expected {self.total_shards} shards, got {len(shards)}"
            )
        size = None
        present = 0
        for s in shards:
            if s is None:
                if not allow_missing:
                    raise ValueError("missing shard")
                continue
            present += 1
            if s.dtype != np.uint8 or s.ndim != 1:
                raise ValueError("shards must be 1-D uint8 arrays")
            if size is None:
                size = s.shape[0]
            elif s.shape[0] != size:
                raise ValueError("shards must all be the same length")
        if size is None or size == 0:
            raise ValueError("no shard data")
        return present

    # --- Encoder surface ---
    def encode(self, shards: list[Optional[np.ndarray]]) -> list[np.ndarray]:
        """Fill shards[k..n-1] with parity computed from shards[0..k-1]."""
        k = self.data_shards
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} shards")
        data = [s for s in shards[:k]]
        if any(s is None for s in data):
            raise ValueError("all data shards required for encode")
        stacked = np.stack(data)  # [k, N]
        parity = self._apply(self.parity_rows, stacked)
        for i in range(self.parity_shards):
            shards[k + i] = parity[i]
        return shards  # type: ignore[return-value]

    def parity_with_crc(
        self, stacked: np.ndarray
    ) -> tuple[np.ndarray, list[int]]:
        """([p, N] parity, [k+p] CRC-32C per shard row) for one [k, N]
        data tile — the HOST side of the fused-CRC stage contract the
        streaming pipeline's device kernels implement on-chip
        (ec/crc_kernel.py): every stage pair hands the writer pool
        (shard bytes, crc) pairs so nothing downstream re-reads the
        bytes to checksum them. Byte- and CRC-identical to the device
        pairs (enforced by tests and bench --check)."""
        from seaweedfs_tpu.util.crc import crc32c

        parity = self._apply(self.parity_rows, stacked)
        crcs = [crc32c(stacked[i].tobytes()) for i in range(self.data_shards)]
        crcs += [crc32c(parity[i].tobytes()) for i in range(self.parity_shards)]
        return parity, crcs

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        self._check_shards(shards, allow_missing=False)
        k = self.data_shards
        stacked = np.stack(shards[:k])
        parity = self._apply(self.parity_rows, stacked)
        for i in range(self.parity_shards):
            if not np.array_equal(parity[i], shards[k + i]):
                return False
        return True

    def _decode_matrix(self, survivors: tuple[int, ...]) -> np.ndarray:
        m = self._decode_cache.get(survivors)
        if m is None:
            sub = gf256.sub_matrix_for_survivors(self.matrix, list(survivors))
            m = gf256.mat_inv(sub)
            self._decode_cache[survivors] = m
        return m

    def decode_rows(
        self, survivors: tuple[int, ...], targets: tuple[int, ...]
    ) -> np.ndarray:
        """Cached [len(targets), k] matrix rebuilding `targets` (data or
        parity) from `survivors` — apply it to the stacked survivor
        tile with `self._apply`."""
        key = (tuple(survivors), tuple(targets))
        rows = self._decode_rows_cache.get(key)
        if rows is None:
            rows = gf256.decode_rows(self.matrix, key[0], key[1])
            if len(self._decode_rows_cache) > 512:
                self._decode_rows_cache.clear()  # bound, rarely hit
            self._decode_rows_cache[key] = rows
        return rows

    def reconstruct(
        self, shards: list[Optional[np.ndarray]], data_only: bool = False
    ) -> list[np.ndarray]:
        """Rebuild missing (None) shards in place.

        Matches the reference library: needs ≥ k present shards; with
        data_only, parity shards are left as None if missing.
        """
        k = self.data_shards
        present = self._check_shards(shards, allow_missing=True)
        missing = [i for i, s in enumerate(shards) if s is None]
        if not missing:
            return shards  # type: ignore[return-value]
        if present < k:
            raise ValueError(
                f"too few shards to reconstruct: {present} of {k} required"
            )

        survivors = tuple(i for i, s in enumerate(shards) if s is not None)[:k]
        stacked = np.stack([shards[i] for i in survivors])  # [k, N]

        missing_data = [i for i in missing if i < k]
        if missing_data:
            decode = self._decode_matrix(survivors)
            rows = decode[np.array(missing_data, dtype=np.intp)]
            rebuilt = self._apply(rows, stacked)
            for j, i in enumerate(missing_data):
                shards[i] = rebuilt[j]

        if not data_only:
            missing_parity = [i for i in missing if i >= k]
            if missing_parity:
                data_stacked = np.stack(shards[:k])  # all data now present
                rows = self.matrix[np.array(missing_parity, dtype=np.intp)]
                rebuilt = self._apply(rows, data_stacked)
                for j, i in enumerate(missing_parity):
                    shards[i] = rebuilt[j]
        return shards  # type: ignore[return-value]

    def reconstruct_data(
        self, shards: list[Optional[np.ndarray]]
    ) -> list[Optional[np.ndarray]]:
        return self.reconstruct(shards, data_only=True)


def new_encoder(
    data_shards: int = DATA_SHARDS,
    parity_shards: int = PARITY_SHARDS,
    backend: str | None = None,
) -> ReedSolomon:
    """Factory mirroring reedsolomon.New(10, 4) (ec_encoder.go:193).

    backend=None picks the process default (`ec.codec` config): tpu on
    hosts with a JAX device, cpu otherwise."""
    return ReedSolomon(data_shards, parity_shards, backend)
