"""Precompiled GF(2^8) XOR/mul schedules (arXiv:2108.02692-style CSE).

Multiplying a byte stream by a CONSTANT GF(2^8) coefficient is
GF(2)-linear, so an RS coefficient matrix is really a straight-line
XOR *program* that can be optimized once at codec construction and
replayed for every tile:

1. **Horner bit realization.** Write each output row as
   ``y[p] = Σ_j 2^j · u_{p,j}`` in the field, where ``u_{p,j}`` is the
   XOR of the input columns whose coefficient has bit j set (the same
   schedule the SWAR Pallas kernel bakes in, codec_tpu._swar_schedule).
   Evaluated Horner-style, a row costs ≤7 branchless GF-doublings plus
   the XOR terms — all full-width SIMD passes, replacing the naive
   chain's per-entry 256-way LUT gathers (the gathers are what hold
   the numpy backend to ~0.1 GB/s; pure bitwise passes run ~2.7x
   faster on the same matrix).

2. **Paar-style common-pair CSE.** The 32 per-(row, bit) XOR sets of
   RS(10,4) share many column pairs. The greedy Paar heuristic (the
   base algorithm the arXiv:2108.02692 schedulers extend) repeatedly
   extracts the most frequent pair into a temp until no pair repeats —
   for this code matrix that cuts 156 XOR terms to 46 plus 24 shared
   temps — so common subexpressions are computed once per tile instead
   of once per use.

Both rewrites are exact — XOR reassociation and GF(2)-linearity hold
bitwise — so scheduled output is byte-identical to the naive chain
(bench.py --check A/Bs the two arms).

The compiler is shared by the numpy backend (ec/codec.py wraps its
apply with the per-matrix program cache here) and the SWAR Pallas
kernel builder (ec/codec_tpu.py runs the same cse_pairs over its
per-bit XOR sets). ``WEED_EC_SCHEDULE=0`` is the kill switch restoring
the naive chains everywhere (read at codec/kernel construction).
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np


def schedule_enabled() -> bool:
    """`WEED_EC_SCHEDULE` env knob: any value but "0" keeps the
    optimized schedules on (kill switch restores the naive chains)."""
    return os.environ.get("WEED_EC_SCHEDULE", "1") != "0"


def cse_pairs(
    sets: Sequence[Sequence[int]], n_inputs: int, max_temps: int | None = None
) -> tuple[list[tuple[int, int]], list[list[int]]]:
    """Greedy Paar pass over XOR sets of input slots 0..n_inputs-1.

    Returns (temps, new_sets): ``temps[t] = (a, b)`` defines slot
    ``n_inputs + t`` as ``slot[a] ^ slot[b]`` (a/b may themselves be
    temps — evaluate in order); every new_sets[i] XORs to the same
    value as sets[i]. Pairs are extracted while any pair of slots
    co-occurs in ≥ 2 sets, most frequent first (ties broken
    deterministically by slot index so compiled programs are stable
    across runs).
    """
    work = [sorted(set(s)) for s in sets]
    temps: list[tuple[int, int]] = []
    next_slot = n_inputs
    while max_temps is None or len(temps) < max_temps:
        counts: dict[tuple[int, int], int] = {}
        for s in work:
            for i in range(len(s)):
                for j in range(i + 1, len(s)):
                    pair = (s[i], s[j])
                    counts[pair] = counts.get(pair, 0) + 1
        if not counts:
            break
        best = max(counts.items(), key=lambda kv: (kv[1], (-kv[0][0], -kv[0][1])))
        if best[1] < 2:
            break
        a, b = best[0]
        temps.append((a, b))
        for idx, s in enumerate(work):
            if a in s and b in s:
                work[idx] = sorted((set(s) - {a, b}) | {next_slot})
        next_slot += 1
    return temps, work


class CompiledSchedule:
    """One matrix's straight-line XOR program: shared temp definitions,
    then per output row a Horner chain over the CSE'd per-bit sets."""

    __slots__ = ("rows", "cols", "temps", "sel", "maxj", "n_terms", "n_terms_naive")

    def __init__(self, matrix: np.ndarray):
        matrix = np.asarray(matrix, dtype=np.uint8)
        self.rows, self.cols = matrix.shape
        sel = [
            [
                [c for c in range(self.cols) if (int(matrix[p, c]) >> j) & 1]
                for j in range(8)
            ]
            for p in range(self.rows)
        ]
        self.maxj = [
            max((j for j in range(8) if sel[p][j]), default=0)
            for p in range(self.rows)
        ]
        self.n_terms_naive = sum(len(s) for row in sel for s in row)
        flat = [sel[p][j] for p in range(self.rows) for j in range(8)]
        self.temps, new_flat = cse_pairs(flat, self.cols)
        it = iter(new_flat)
        self.sel = [[next(it) for _ in range(8)] for _ in range(self.rows)]
        self.n_terms = sum(len(s) for row in self.sel for s in row)

    def apply(self, inputs: np.ndarray) -> np.ndarray:
        """inputs [C, N] uint8 → [R, N] uint8, byte-identical to
        codec.cpu_apply_matrix on the same matrix."""
        assert inputs.shape[0] == self.cols
        slots: list[np.ndarray] = [inputs[c] for c in range(self.cols)]
        for a, b in self.temps:
            slots.append(slots[a] ^ slots[b])
        n = inputs.shape[1]
        out = np.empty((self.rows, n), dtype=np.uint8)
        red = np.uint8(0x1D)
        hb = np.empty(n, dtype=np.uint8)  # doubling scratch, reused
        for p in range(self.rows):
            y = out[p]
            live = False
            for j in range(self.maxj[p], -1, -1):
                if live:
                    # branchless GF(2^8) doubling on uint8 lanes:
                    # y' = (y << 1) ^ 0x1D·highbit(y)  (poly 0x11D)
                    np.right_shift(y, 7, out=hb)
                    np.left_shift(y, 1, out=y)
                    hb *= red
                    y ^= hb
                s = self.sel[p][j]
                if s:
                    if live:
                        for c in s:
                            y ^= slots[c]
                    else:
                        np.copyto(y, slots[s[0]])
                        for c in s[1:]:
                            y ^= slots[c]
                        live = True
            if not live:
                y.fill(0)
        return out


# (shape, matrix bytes) -> CompiledSchedule. Distinct matrices are few:
# the parity rows plus one decode-rows matrix per survivor/target pair,
# each already cached in its own right upstream.
_PROGRAM_CACHE: dict[tuple, CompiledSchedule] = {}


def compile_schedule(matrix: np.ndarray) -> CompiledSchedule:
    m = np.asarray(matrix, dtype=np.uint8)
    key = (m.shape, m.tobytes())
    prog = _PROGRAM_CACHE.get(key)
    if prog is None:
        if len(_PROGRAM_CACHE) > 512:
            _PROGRAM_CACHE.clear()  # bound, rarely hit
        prog = _PROGRAM_CACHE[key] = CompiledSchedule(m)
    return prog


def scheduled_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """Drop-in for codec.cpu_apply_matrix running the compiled
    program (compiled once per distinct matrix, then replayed)."""
    return compile_schedule(matrix).apply(inputs)
