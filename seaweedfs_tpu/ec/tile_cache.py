"""Per-volume LRU cache of reconstructed EC shard tiles.

A degraded read — a GET whose interval lands on a lost/quarantined
shard — must decode that interval from k surviving shards. The decode
input is k× the output and the gather usually crosses the rack, so
re-decoding the same hot range for every GET multiplies both CPU and
network by the read rate. This cache remembers the *reconstructed
bytes* at fixed tile granularity: the first degraded read of a tile
pays the k-shard gather + decode once, every later read of any
interval inside it is a memcpy.

Correctness leans on two facts:

  * RS reconstruction is deterministic — any k survivors produce the
    same bytes — so a cached tile is byte-identical to a fresh decode
    no matter which survivor set either used;
  * shard bytes are immutable while mounted (deletes tombstone the
    .ecx, never touch shard files), so the only events that can change
    what a decode would return are shard remount (a rebuild landed a
    regenerated file), quarantine, and rebuild itself — EcVolume
    invalidates on each.

Scan resistance (segmented admission): a sequential scan through a
dead shard touches every tile exactly once; in a plain LRU those
one-touch tiles march straight through and evict the hot set. Tiles
therefore land in a small PROBATION segment first (bounded at
capacity/8, min one tile) and are only promoted to the protected
segment on a second touch — a get() hit while still probationary.
Scans churn probation only; eviction under global pressure drains
probation before it ever considers a protected tile.
WEED_EC_TILE_SCAN=0 restores the plain single-segment LRU wholesale.

The cache is per-EcVolume (dropped wholesale with the volume), bounded
in bytes, and safe for concurrent readers. Knobs (docs/OPERATIONS.md
env table): WEED_EC_TILE_CACHE=0 disables, WEED_EC_TILE_CACHE_MB
bounds the per-volume footprint (default 64), WEED_EC_TILE_BYTES sets
the tile granularity (default 256 KiB), WEED_EC_TILE_SCAN=0 disables
the probationary segment.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from seaweedfs_tpu.stats.metrics import EC_TILE_CACHE

DEFAULT_TILE_BYTES = 256 * 1024
DEFAULT_CAPACITY_MB = 64


def _int_or(raw: str, default: int) -> int:
    try:
        return int(raw)
    except ValueError:
        return default


class TileCache:
    """Segmented LRU of (shard_id, tile_offset) -> reconstructed bytes:
    probation (one-touch, scan-churned) + protected (second-touch)."""

    def __init__(
        self,
        capacity_bytes: int | None = None,
        tile_bytes: int | None = None,
    ):
        # literal env reads so the weedlint contract tier can cross-
        # check each knob against the OPERATIONS.md table
        if capacity_bytes is None:
            capacity_bytes = _int_or(
                os.environ.get(
                    "WEED_EC_TILE_CACHE_MB", str(DEFAULT_CAPACITY_MB)
                ),
                DEFAULT_CAPACITY_MB,
            ) << 20
        if tile_bytes is None:
            tile_bytes = _int_or(
                os.environ.get(
                    "WEED_EC_TILE_BYTES", str(DEFAULT_TILE_BYTES)
                ),
                DEFAULT_TILE_BYTES,
            )
        self.capacity_bytes = max(0, capacity_bytes)
        self.tile_bytes = max(4096, tile_bytes)
        if os.environ.get("WEED_EC_TILE_CACHE", "1") == "0":
            self.capacity_bytes = 0
        self.scan_resistant = (
            os.environ.get("WEED_EC_TILE_SCAN", "1") != "0"
        )
        # probation stays SMALL: a scan can only ever churn this much
        self.probation_bytes_cap = min(
            self.capacity_bytes,
            max(self.tile_bytes, self.capacity_bytes // 8),
        )
        self._lock = threading.Lock()
        self._probation: OrderedDict[tuple[int, int], bytes] = OrderedDict()
        self._protected: OrderedDict[tuple[int, int], bytes] = OrderedDict()
        self._prob_bytes = 0
        self._prot_bytes = 0
        self.invalidations = 0

    @property
    def enabled(self) -> bool:
        return self.capacity_bytes > 0

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._prob_bytes + self._prot_bytes

    def _evict_over_bounds(self) -> None:
        """Lock held. Probation to its own cap, then the global bound —
        probation drains first, protected only under residual
        pressure (how a scan never touches the hot set)."""
        while self._prob_bytes > self.probation_bytes_cap and self._probation:
            _, v = self._probation.popitem(last=False)
            self._prob_bytes -= len(v)
        while (
            self._prob_bytes + self._prot_bytes > self.capacity_bytes
        ) and (self._probation or self._protected):
            if self._probation:
                _, v = self._probation.popitem(last=False)
                self._prob_bytes -= len(v)
            else:
                _, v = self._protected.popitem(last=False)
                self._prot_bytes -= len(v)

    def get(self, shard_id: int, tile_off: int) -> bytes | None:
        """Counted probe (hit/miss land on weed_ec_tile_cache_total).
        A probationary hit is the second touch: the tile promotes to
        the protected segment."""
        key = (shard_id, tile_off)
        with self._lock:
            data = self._protected.get(key)
            if data is not None:
                self._protected.move_to_end(key)
            else:
                data = self._probation.get(key)
                if data is not None:
                    # second touch: promote
                    del self._probation[key]
                    self._prob_bytes -= len(data)
                    self._protected[key] = data
                    self._prot_bytes += len(data)
                    self._evict_over_bounds()
        EC_TILE_CACHE.labels("hit" if data is not None else "miss").inc()
        return data

    def covers(self, shard_id: int, offset: int, size: int) -> bool:
        """Uncounted probe: True when every tile of [offset, offset+size)
        is resident — lets the read path prefer memory over a remote
        shard fetch without charging a miss for merely asking."""
        if not self.enabled or size <= 0:
            return False
        tile = self.tile_bytes
        t = (offset // tile) * tile
        with self._lock:
            while t < offset + size:
                data = self._protected.get((shard_id, t))
                if data is None:
                    data = self._probation.get((shard_id, t))
                if data is None or t + len(data) < min(offset + size, t + tile):
                    return False
                t += tile
        return True

    def put(
        self,
        shard_id: int,
        tile_off: int,
        data: bytes,
        gen: int | None = None,
    ) -> bool:
        """Insert a tile; returns True when it landed. `gen` is the
        invalidation generation captured BEFORE the decode started
        (self.invalidations): an invalidation that raced the decode —
        e.g. a survivor quarantined mid-gather may have contributed
        corrupt bytes — makes the stale insert a no-op instead of
        poisoning the cache forever (checked under the same lock
        invalidate() increments under).

        New tiles are admitted to PROBATION (or straight to the single
        segment with WEED_EC_TILE_SCAN=0); a re-put of an already
        protected tile updates it in place."""
        if not self.enabled or not data:
            return False
        key = (shard_id, tile_off)
        with self._lock:
            if gen is not None and gen != self.invalidations:
                return False
            old = self._protected.pop(key, None)
            if old is not None:
                # already earned protection: refresh in place
                self._prot_bytes -= len(old)
                self._protected[key] = data
                self._prot_bytes += len(data)
                self._evict_over_bounds()
                return True
            old = self._probation.pop(key, None)
            if old is not None:
                self._prob_bytes -= len(old)
            if self.scan_resistant:
                self._probation[key] = data
                self._prob_bytes += len(data)
            else:
                self._protected[key] = data
                self._prot_bytes += len(data)
            self._evict_over_bounds()
        return True

    def snapshot(self, shard_id: int) -> list[tuple[int, bytes]]:
        """Resident tiles of one shard, (tile_off, bytes) — the rebuild
        piggyback drains these at session open so degraded traffic that
        already ran still counts toward repair forward-progress.
        Probationary tiles count too: their bytes are just as decoded."""
        with self._lock:
            out = [
                (off, data)
                for (sid, off), data in self._protected.items()
                if sid == shard_id
            ]
            out += [
                (off, data)
                for (sid, off), data in self._probation.items()
                if sid == shard_id
            ]
            return out

    def invalidate(self) -> None:
        """Drop everything (shard remount / quarantine / rebuild: the
        decode inputs changed, cached outputs may no longer match)."""
        with self._lock:
            self._probation.clear()
            self._protected.clear()
            self._prob_bytes = 0
            self._prot_bytes = 0
            self.invalidations += 1
