"""Per-volume LRU cache of reconstructed EC shard tiles.

A degraded read — a GET whose interval lands on a lost/quarantined
shard — must decode that interval from k surviving shards. The decode
input is k× the output and the gather usually crosses the rack, so
re-decoding the same hot range for every GET multiplies both CPU and
network by the read rate. This cache remembers the *reconstructed
bytes* at fixed tile granularity: the first degraded read of a tile
pays the k-shard gather + decode once, every later read of any
interval inside it is a memcpy.

Correctness leans on two facts:

  * RS reconstruction is deterministic — any k survivors produce the
    same bytes — so a cached tile is byte-identical to a fresh decode
    no matter which survivor set either used;
  * shard bytes are immutable while mounted (deletes tombstone the
    .ecx, never touch shard files), so the only events that can change
    what a decode would return are shard remount (a rebuild landed a
    regenerated file), quarantine, and rebuild itself — EcVolume
    invalidates on each.

The cache is per-EcVolume (dropped wholesale with the volume), bounded
in bytes, and safe for concurrent readers. Knobs (docs/OPERATIONS.md
env table): WEED_EC_TILE_CACHE=0 disables, WEED_EC_TILE_CACHE_MB
bounds the per-volume footprint (default 64), WEED_EC_TILE_BYTES sets
the tile granularity (default 256 KiB).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from seaweedfs_tpu.stats.metrics import EC_TILE_CACHE

DEFAULT_TILE_BYTES = 256 * 1024
DEFAULT_CAPACITY_MB = 64


def _int_or(raw: str, default: int) -> int:
    try:
        return int(raw)
    except ValueError:
        return default


class TileCache:
    """LRU of (shard_id, tile_offset) -> reconstructed bytes."""

    def __init__(
        self,
        capacity_bytes: int | None = None,
        tile_bytes: int | None = None,
    ):
        # literal env reads so the weedlint contract tier can cross-
        # check each knob against the OPERATIONS.md table
        if capacity_bytes is None:
            capacity_bytes = _int_or(
                os.environ.get(
                    "WEED_EC_TILE_CACHE_MB", str(DEFAULT_CAPACITY_MB)
                ),
                DEFAULT_CAPACITY_MB,
            ) << 20
        if tile_bytes is None:
            tile_bytes = _int_or(
                os.environ.get(
                    "WEED_EC_TILE_BYTES", str(DEFAULT_TILE_BYTES)
                ),
                DEFAULT_TILE_BYTES,
            )
        self.capacity_bytes = max(0, capacity_bytes)
        self.tile_bytes = max(4096, tile_bytes)
        if os.environ.get("WEED_EC_TILE_CACHE", "1") == "0":
            self.capacity_bytes = 0
        self._lock = threading.Lock()
        self._tiles: OrderedDict[tuple[int, int], bytes] = OrderedDict()
        self._bytes = 0
        self.invalidations = 0

    @property
    def enabled(self) -> bool:
        return self.capacity_bytes > 0

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, shard_id: int, tile_off: int) -> bytes | None:
        """Counted probe (hit/miss land on weed_ec_tile_cache_total)."""
        with self._lock:
            data = self._tiles.get((shard_id, tile_off))
            if data is not None:
                self._tiles.move_to_end((shard_id, tile_off))
        EC_TILE_CACHE.labels("hit" if data is not None else "miss").inc()
        return data

    def covers(self, shard_id: int, offset: int, size: int) -> bool:
        """Uncounted probe: True when every tile of [offset, offset+size)
        is resident — lets the read path prefer memory over a remote
        shard fetch without charging a miss for merely asking."""
        if not self.enabled or size <= 0:
            return False
        tile = self.tile_bytes
        t = (offset // tile) * tile
        with self._lock:
            while t < offset + size:
                data = self._tiles.get((shard_id, t))
                if data is None or t + len(data) < min(offset + size, t + tile):
                    return False
                t += tile
        return True

    def put(
        self,
        shard_id: int,
        tile_off: int,
        data: bytes,
        gen: int | None = None,
    ) -> bool:
        """Insert a tile; returns True when it landed. `gen` is the
        invalidation generation captured BEFORE the decode started
        (self.invalidations): an invalidation that raced the decode —
        e.g. a survivor quarantined mid-gather may have contributed
        corrupt bytes — makes the stale insert a no-op instead of
        poisoning the cache forever (checked under the same lock
        invalidate() increments under)."""
        if not self.enabled or not data:
            return False
        with self._lock:
            if gen is not None and gen != self.invalidations:
                return False
            old = self._tiles.pop((shard_id, tile_off), None)
            if old is not None:
                self._bytes -= len(old)
            self._tiles[(shard_id, tile_off)] = data
            self._bytes += len(data)
            while self._bytes > self.capacity_bytes and self._tiles:
                _, evicted = self._tiles.popitem(last=False)
                self._bytes -= len(evicted)
        return True

    def snapshot(self, shard_id: int) -> list[tuple[int, bytes]]:
        """Resident tiles of one shard, (tile_off, bytes) — the rebuild
        piggyback drains these at session open so degraded traffic that
        already ran still counts toward repair forward-progress."""
        with self._lock:
            return [
                (off, data)
                for (sid, off), data in self._tiles.items()
                if sid == shard_id
            ]

    def invalidate(self) -> None:
        """Drop everything (shard remount / quarantine / rebuild: the
        decode inputs changed, cached outputs may no longer match)."""
        with self._lock:
            self._tiles.clear()
            self._bytes = 0
            self.invalidations += 1
