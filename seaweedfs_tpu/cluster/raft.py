"""Compact Raft for the HA master tier.

Role match of reference weed/server/raft_server.go:28-88 (which embeds
chrislusf/raft over a gRPC transport): leader election + a replicated
command log whose only production command is MaxVolumeId
(weed/topology/cluster_commands.go). The log is tiny — one entry per
volume-id allocation — so no snapshotting/compaction is needed; the
whole persistent state (term, vote, log) lives in one JSON file per
node, rewritten atomically on change.

Safety properties implemented per the Raft paper (§5.1-5.4):
  * one vote per term, persisted before replying
  * election restriction: candidates must have an up-to-date log
  * append consistency check on (prev_log_index, prev_log_term) with
    conflict truncation
  * commit only log entries of the current term via majority match
    (older entries commit transitively)

Threading model: a single ticker thread drives election timeouts and
leader heartbeats; RPC handlers run on gRPC server threads; all state
transitions hold one lock. propose() blocks until the entry commits
(applying is done in commit order under the same lock discipline).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable

import grpc

from seaweedfs_tpu.pb import raft_pb2 as rpb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.util import durable

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeader(Exception):
    def __init__(self, leader: str):
        super().__init__(f"not the leader; leader={leader or 'unknown'}")
        self.leader = leader


class RaftNode:
    def __init__(
        self,
        self_addr: str,
        peers: list[str],
        apply_fn: Callable[[dict], None],
        data_dir: str | None = None,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        heartbeat_interval: float = 0.1,
    ):
        """self_addr/peers are master HTTP addresses ("host:port");
        the raft RPCs ride each master's gRPC port (+10000).
        apply_fn(command_dict) is invoked in log order on every node
        as entries commit. Election timeouts are 4-8x the heartbeat
        interval so GIL/CPU starvation in crowded test hosts does not
        read as leader death and churn elections."""
        self.self_addr = self_addr
        self.peers = [p for p in peers if p != self_addr]
        self.apply_fn = apply_fn
        self.data_dir = data_dir
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self._lock = threading.Lock()
        self._commit_cv = threading.Condition(self._lock)
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for = ""
        self.log: list[rpb.LogEntry] = []  # 1-based indexing via entry.index
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id = ""
        self._deadline = time.monotonic() + self._rand_timeout()
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        self._channels: dict[str, grpc.Channel] = {}
        # leader-side per-peer replicator threads + wakeup events
        self._repl_threads: list[threading.Thread] = []
        self._repl_events: dict[str, threading.Event] = {}

        self._load_state()

    # ------------------------------------------------------------------
    # persistence (raft paper: persist term/vote/log before replying)
    def _state_path(self) -> str | None:
        if not self.data_dir:
            return None
        return os.path.join(
            self.data_dir, f"raft-{self.self_addr.replace(':', '_')}.json"
        )

    def _load_state(self) -> None:
        path = self._state_path()
        if not path or not os.path.exists(path):
            return
        with open(path) as f:
            st = json.load(f)
        self.current_term = st.get("term", 0)
        self.voted_for = st.get("voted_for", "")
        self.log = [
            rpb.LogEntry(term=e["term"], index=e["index"], command=e["command"])
            for e in st.get("log", [])
        ]

    def _persist(self) -> None:
        path = self._state_path()
        if not path:
            return
        os.makedirs(self.data_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "term": self.current_term,
                    "voted_for": self.voted_for,
                    "log": [
                        {"term": e.term, "index": e.index, "command": e.command}
                        for e in self.log
                    ],
                },
                f,
            )
        # durable publish: a vote or term bump that does not survive
        # the crash lets this node vote twice in one term — the one
        # thing Raft's safety argument forbids
        durable.publish(tmp, path)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        for ev in self._repl_events.values():
            ev.set()
        if self._ticker:
            self._ticker.join(timeout=2)
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    def leader(self) -> str:
        if self.role == LEADER:
            return self.self_addr
        return self.leader_id

    # ------------------------------------------------------------------
    # log helpers (under lock)
    def _last_log_index(self) -> int:
        return self.log[-1].index if self.log else 0

    def _last_log_term(self) -> int:
        return self.log[-1].term if self.log else 0

    def _entry_at(self, index: int) -> rpb.LogEntry | None:
        if index <= 0 or index > len(self.log):
            return None
        return self.log[index - 1]

    def _rand_timeout(self) -> float:
        return random.uniform(*self.election_timeout)

    def _become_follower(self, term: int) -> None:
        self.role = FOLLOWER
        self.current_term = term
        self.voted_for = ""
        self.leader_id = ""  # unknown for the new term until a leader speaks
        self._deadline = time.monotonic() + self._rand_timeout()
        self._persist()

    # ------------------------------------------------------------------
    # RPC handlers (bound into the master's gRPC server)
    def RequestVote(self, req: rpb.RequestVoteRequest, context=None):
        with self._lock:
            if req.term > self.current_term:
                self._become_follower(req.term)
            granted = False
            if req.term == self.current_term and self.voted_for in (
                "",
                req.candidate_id,
            ):
                # election restriction (§5.4.1): candidate's log must be
                # at least as up-to-date as ours
                up_to_date = req.last_log_term > self._last_log_term() or (
                    req.last_log_term == self._last_log_term()
                    and req.last_log_index >= self._last_log_index()
                )
                if up_to_date:
                    granted = True
                    self.voted_for = req.candidate_id
                    self._deadline = time.monotonic() + self._rand_timeout()
                    self._persist()
            return rpb.RequestVoteResponse(
                term=self.current_term, vote_granted=granted
            )

    def AppendEntries(self, req: rpb.AppendEntriesRequest, context=None):
        with self._lock:
            if req.term > self.current_term:
                self._become_follower(req.term)
            if req.term < self.current_term:
                return rpb.AppendEntriesResponse(
                    term=self.current_term, success=False
                )
            # valid leader for this term
            self.role = FOLLOWER
            self.leader_id = req.leader_id
            self._deadline = time.monotonic() + self._rand_timeout()

            # consistency check
            if req.prev_log_index > 0:
                prev = self._entry_at(req.prev_log_index)
                if prev is None or prev.term != req.prev_log_term:
                    return rpb.AppendEntriesResponse(
                        term=self.current_term, success=False
                    )
            # append, truncating conflicts
            changed = False
            for e in req.entries:
                existing = self._entry_at(e.index)
                if existing is not None and existing.term != e.term:
                    del self.log[e.index - 1 :]
                    existing = None
                    changed = True
                if existing is None:
                    self.log.append(
                        rpb.LogEntry(term=e.term, index=e.index, command=e.command)
                    )
                    changed = True
            if changed:
                self._persist()
            if req.leader_commit > self.commit_index:
                self.commit_index = min(req.leader_commit, self._last_log_index())
                self._apply_committed_locked()
            return rpb.AppendEntriesResponse(
                term=self.current_term,
                success=True,
                # only what THIS request proved replicated: stale
                # entries past prev+entries may conflict with the
                # leader's log and must not count toward commit
                match_index=req.prev_log_index + len(req.entries),
            )

    # ------------------------------------------------------------------
    # ticker: elections + leader heartbeats
    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                role = self.role
                deadline = self._deadline
            now = time.monotonic()
            if role == LEADER:
                # per-peer replicator threads carry heartbeats; one
                # slow/dead peer must not gate the others' cadence
                self._stop.wait(self.heartbeat_interval)
            elif now >= deadline:
                self._run_election()
            else:
                self._stop.wait(min(0.02, deadline - now))

    def _run_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.self_addr
            term = self.current_term
            self._deadline = time.monotonic() + self._rand_timeout()
            self._persist()
            req = rpb.RequestVoteRequest(
                term=term,
                candidate_id=self.self_addr,
                last_log_index=self._last_log_index(),
                last_log_term=self._last_log_term(),
            )
        votes = 1  # self
        needed = (len(self.peers) + 1) // 2 + 1
        results: list[rpb.RequestVoteResponse] = []
        lock = threading.Lock()
        done = threading.Event()

        def ask(peer: str) -> None:
            nonlocal votes
            resp = self._call(peer, "RequestVote", req, timeout=0.2)
            if resp is None:
                return
            with lock:
                results.append(resp)
                if resp.vote_granted:
                    votes += 1
                    if votes >= needed:
                        done.set()

        threads = [
            threading.Thread(target=ask, args=(p,), daemon=True)
            for p in self.peers
        ]
        for t in threads:
            t.start()
        done.wait(timeout=0.3)
        with self._lock:
            for resp in results:
                if resp.term > self.current_term:
                    self._become_follower(resp.term)
                    return
            if self.role != CANDIDATE or self.current_term != term:
                return
            if votes >= needed:
                self.role = LEADER
                self.leader_id = self.self_addr
                nxt = self._last_log_index() + 1
                self._next_index = {p: nxt for p in self.peers}
                self._match_index = {p: 0 for p in self.peers}
                # commit a current-term no-op immediately so entries
                # from prior terms become committable (§5.4.2 — a new
                # leader may never commit old-term entries directly)
                self.log.append(
                    rpb.LogEntry(
                        term=self.current_term,
                        index=nxt,
                        command=json.dumps({"name": "Noop"}),
                    )
                )
                self._persist()
        if self.is_leader:
            self._start_replicators()
            # single-node cluster: commit advances with no peers to wait on
            self._advance_commit()

    def _start_replicators(self) -> None:
        """One long-lived replicator thread per peer: sends
        AppendEntries immediately when woken (new entries) and at the
        heartbeat interval otherwise. A dead peer blocks only its own
        thread, never the other peers' heartbeat cadence."""
        with self._lock:
            term = self.current_term
        self._repl_events = {p: threading.Event() for p in self.peers}

        def run(peer: str) -> None:
            ev = self._repl_events[peer]
            while not self._stop.is_set():
                with self._lock:
                    if self.role != LEADER or self.current_term != term:
                        return
                self._replicate_to(peer)
                ev.wait(timeout=self.heartbeat_interval)
                ev.clear()

        self._repl_threads = [
            threading.Thread(target=run, args=(p,), daemon=True)
            for p in self.peers
        ]
        for t in self._repl_threads:
            t.start()

    def _wake_replicators(self) -> None:
        for ev in self._repl_events.values():
            ev.set()

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            nxt = self._next_index.get(peer, self._last_log_index() + 1)
            prev_index = nxt - 1
            prev = self._entry_at(prev_index)
            req = rpb.AppendEntriesRequest(
                term=self.current_term,
                leader_id=self.self_addr,
                prev_log_index=prev_index,
                prev_log_term=prev.term if prev else 0,
                leader_commit=self.commit_index,
            )
            for e in self.log[nxt - 1 :]:
                req.entries.add(term=e.term, index=e.index, command=e.command)
        resp = self._call(peer, "AppendEntries", req, timeout=0.2)
        if resp is None:
            return
        with self._lock:
            if resp.term > self.current_term:
                self._become_follower(resp.term)
                return
            if self.role != LEADER:
                return
            if resp.success:
                self._match_index[peer] = resp.match_index
                self._next_index[peer] = resp.match_index + 1
            else:
                # back off and retry next round
                self._next_index[peer] = max(1, self._next_index.get(peer, 1) - 1)
        if resp.success:
            self._advance_commit()

    def _advance_commit(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            majority = (len(self.peers) + 1) // 2 + 1
            for idx in range(self._last_log_index(), self.commit_index, -1):
                entry = self._entry_at(idx)
                if entry is None or entry.term != self.current_term:
                    continue  # §5.4.2: only current-term entries directly
                count = 1 + sum(
                    1 for p in self.peers if self._match_index.get(p, 0) >= idx
                )
                if count >= majority:
                    self.commit_index = idx
                    self._apply_committed_locked()
                    break

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entry_at(self.last_applied)
            if entry is not None and entry.command:
                try:
                    self.apply_fn(json.loads(entry.command))
                except Exception:  # noqa: BLE001 - state machine must not kill raft
                    pass
        self._commit_cv.notify_all()

    # ------------------------------------------------------------------
    def propose(self, command: dict, timeout: float = 5.0) -> None:
        """Leader-only: append `command`, replicate, block until it
        commits (and is applied locally). Raises NotLeader elsewhere."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeader(self.leader())
            index = self._last_log_index() + 1
            self.log.append(
                rpb.LogEntry(
                    term=self.current_term, index=index, command=json.dumps(command)
                )
            )
            self._persist()
        self._wake_replicators()
        self._advance_commit()  # single-node clusters commit immediately
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.last_applied < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError(f"command at index {index} did not commit")
                if self.role != LEADER:
                    raise NotLeader(self.leader())
                self._commit_cv.wait(timeout=min(remaining, 0.05))

    def barrier(self, timeout: float = 5.0) -> None:
        """Leader-only: block until every entry currently in the log is
        applied locally. A freshly elected leader may hold committed-
        but-unapplied entries from prior terms (its no-op commits
        them); reading state-machine values (max volume id) before the
        backlog applies would hand out stale answers."""
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            if self.role != LEADER:
                raise NotLeader(self.leader())
            target = self._last_log_index()
            while self.last_applied < target:
                if self.role != LEADER:
                    raise NotLeader(self.leader())
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError("raft apply backlog did not drain")
                self._commit_cv.wait(timeout=min(remaining, 0.05))

    # ------------------------------------------------------------------
    def _channel(self, peer: str) -> grpc.Channel:
        ch = self._channels.get(peer)
        if ch is None:
            ch = rpc.dial(rpc.grpc_address(peer))
            self._channels[peer] = ch
        return ch

    def _call(self, peer: str, method: str, req, timeout: float):
        try:
            stub = rpc.raft_stub(self._channel(peer))
            return getattr(stub, method)(req, timeout=timeout)
        except grpc.RpcError:
            return None
