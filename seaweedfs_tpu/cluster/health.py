"""weedguard: master-side node health scoring (docs/HEALTH.md).

The cluster's liveness model used to be binary — a node is alive while
its heartbeat stream is up (plus the node_timeout sweep), dead after.
The warehouse-cluster failure study (arXiv:1309.0186) and every
production postmortem about SIGSTOP'd/gray nodes say the interesting
failures live BETWEEN those states: a frozen process keeps its TCP
sessions open and stays in the write-assignment pool while every
request into it times out; a node with a dying disk serves EIO for
minutes before anything reacts.

This module scores every data node from three independent signal
families and drives a `healthy → suspect → dead` state machine with
hysteresis:

  * **phi-accrual suspicion** from heartbeat inter-arrival times
    (Hayashibara et al.): the master learns each node's own beat
    cadence and asks "how improbable is the current silence?" — a
    SIGSTOP'd node that never disconnects goes suspect within a few
    missed beats, long before the coarse node_timeout sweep;
  * **error EWMAs** fed from heartbeat-reported cumulative counters
    (EIO/ENOSPC seen serving, 5xx responses served) — a node that is
    reachable but failing work goes suspect too;
  * **operator/self-reported flags**: the volume server's local disk
    watchdog announces `lame_duck` (read-only after repeated IO
    errors), SIGTERM announces `draining`, and `node.drain` registers
    an operator drain master-side. These exclude the node from write
    assignment without demoting its reads.

Consumers (all master-side, so the whole cluster sees ONE verdict):
`pick_for_write` prefers volumes whose replicas are all assignable,
lookup responses order suspect replicas last and mark them
(`Location.suspect`) so clients demote them cluster-wide and the hedge
driver fires eagerly, and the RepairScheduler moves data off draining
nodes.

`WEED_HEALTH=0` kills the plane wholesale: every node reports healthy,
placement/serving revert to pre-health behavior, and replica-write
failures fail the write again (no hinted handoff).
"""

from __future__ import annotations

import math
import os
import threading
import time

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"


def enabled() -> bool:
    """Plane kill switch (WEED_HEALTH=0 restores pre-health behavior
    wholesale); read per call like the QoS switches so tests and
    operator restarts can flip it without import-order games."""
    return os.environ.get("WEED_HEALTH", "1") != "0"


def phi_threshold() -> float:
    """Suspicion threshold on the phi scale (WEED_HEALTH_PHI, default
    8 ≈ "this silence had a 10^-8 chance under the learned cadence").
    Lower = faster detection, more false suspects."""
    try:
        return float(os.environ.get("WEED_HEALTH_PHI", "8"))
    except ValueError:
        return 8.0


def err_ewma_threshold() -> float:
    """Errors-per-beat EWMA above which a node goes suspect
    (WEED_HEALTH_ERR_EWMA, default 3)."""
    try:
        return float(os.environ.get("WEED_HEALTH_ERR_EWMA", "3"))
    except ValueError:
        return 3.0


def recover_s() -> float:
    """Hysteresis hold-down (WEED_HEALTH_RECOVER_S, default 5):
    once suspect, a node must stay clean this long before it reads as
    healthy again — a gray node flapping across the phi threshold must
    not flap the assignment pool with it."""
    try:
        return float(os.environ.get("WEED_HEALTH_RECOVER_S", "5"))
    except ValueError:
        return 5.0


class PhiAccrual:
    """Phi-accrual failure detector over one node's heartbeat
    inter-arrival times (a ring of recent intervals; normal-tail
    approximation like Akka/Cassandra's detectors).

    phi(now) = -log10(P(interval > now - last_arrival)) under a normal
    fit of the observed intervals, with the std floored so a perfectly
    regular beat doesn't make the detector infinitely twitchy."""

    _RING = 32
    _MIN_SAMPLES = 3
    # floors: beats are scheduler-jittery at millisecond scale, and a
    # zero std would turn one late packet into phi=inf
    _MIN_STD_FRAC = 0.15
    _MIN_STD_S = 0.05
    # suspicion gate: silence only counts once it exceeds this multiple
    # of the WORST inter-arrival gap in the ring. Heartbeats are not a
    # pure tick — inventory changes fire forced delta beats in bursts
    # of near-zero intervals that drag the learned mean far below the
    # real cadence, and without the gate the next NORMAL tick read as
    # a phi spike (a healthy node flapping suspect right after
    # registering volumes — found by the SIGSTOP scenario, where the
    # flap emptied the clean assignment pool). Extra beats can only
    # make silence LESS alarming, never more.
    _GATE_FACTOR = 2.0
    # burst intervals below this never enter the ring: forced beats
    # land milliseconds apart and say nothing about the tick cadence —
    # a ring full of them (a node registering 7 volumes before its
    # first regular beat) would make the FIRST normal tick read as a
    # phi spike and defeat the gate above (max of a pure-burst ring is
    # itself tiny)
    _MIN_GAP_S = 0.02

    # a beat ENDING a silence the detector itself flagged suspicious is
    # an outage resume, not cadence — recording it would poison the
    # gate (max(intervals) jumps to the outage length, blinding the
    # NEXT gray failure for up to a full ring). But a permanently
    # skipped sample must not exist either — an operator restarting
    # with a 20× slower -heartbeat would read suspect forever — so
    # after this many consecutive skips the next interval is accepted
    # and the ring re-learns the new cadence.
    _MAX_SKIPS = 3

    __slots__ = ("_intervals", "_pos", "last_arrival", "_skipped")

    def __init__(self) -> None:
        self._intervals: list[float] = []
        self._pos = 0
        self.last_arrival = 0.0
        self._skipped = 0

    def observe(self, now: float) -> None:
        if self.last_arrival:
            iv = now - self.last_arrival
            suspicious = (
                self.phi(now) > phi_threshold()
                and self._skipped < self._MAX_SKIPS
            )
            if suspicious:
                self._skipped += 1
            elif iv >= self._MIN_GAP_S:
                self._skipped = 0
                if len(self._intervals) < self._RING:
                    self._intervals.append(iv)
                else:
                    self._intervals[self._pos] = iv
                    self._pos = (self._pos + 1) % self._RING
        self.last_arrival = now

    def warmed(self) -> bool:
        """Enough cadence history that silence CAN raise phi. Surfaced
        on /cluster/health so operators (and e2e rigs) can barrier on
        the detector being armed instead of sleeping a fixed number of
        beats — a wall-clock warm-up assumes the configured cadence,
        and on a loaded host the beat thread can run late enough that
        the sleep ends with fewer than _MIN_SAMPLES intervals in the
        ring, leaving phi pinned at 0."""
        return len(self._intervals) >= self._MIN_SAMPLES

    def gate_s(self) -> float:
        """The current suspicion gate in seconds: silence shorter than
        this reads as phi=0 (see _GATE_FACTOR). This is the LEARNED
        earliest-detection horizon — it tracks the worst observed
        inter-arrival gap, not the configured tick, so any promptness
        expectation (alert SLO, test bound) must be stated relative to
        it rather than to `-heartbeat`."""
        if not self._intervals:
            return 0.0
        return self._GATE_FACTOR * max(self._intervals)

    def phi(self, now: float) -> float:
        """0 while within the learned cadence; grows without bound as
        the silence stretches. 0 before enough history exists (a brand
        new node must not be born suspect)."""
        if not self.last_arrival or len(self._intervals) < self._MIN_SAMPLES:
            return 0.0
        elapsed = now - self.last_arrival
        if elapsed <= self._GATE_FACTOR * max(self._intervals):
            return 0.0
        n = len(self._intervals)
        mean = sum(self._intervals) / n
        var = sum((x - mean) ** 2 for x in self._intervals) / n
        std = max(math.sqrt(var), mean * self._MIN_STD_FRAC, self._MIN_STD_S)
        z = (elapsed - mean) / std
        if z <= 0:
            return 0.0
        # P(X > elapsed) for a normal tail; the log-survival form keeps
        # precision where the probability underflows a float
        p = 0.5 * math.erfc(z / math.sqrt(2.0))
        if p <= 0.0:
            # erfc underflow: asymptotic log10 tail, still monotone in z
            return (z * z) / (2.0 * math.log(10.0))
        return -math.log10(p)


class NodeHealth:
    """One node's live health record on the master."""

    __slots__ = (
        "url", "detector", "err_ewma", "_last_io_errors",
        "_last_request_errors", "lame_duck", "draining", "drain_requested",
        "scrub_flagged", "dead", "dead_since", "_suspect_until",
        "_last_reasons",
    )

    _EWMA_ALPHA = 0.3

    def __init__(self, url: str):
        self.url = url
        self.detector = PhiAccrual()
        self.err_ewma = 0.0
        self._last_io_errors = 0
        self._last_request_errors = 0
        self.lame_duck = False
        self.draining = False           # self-announced (SIGTERM drain)
        self.drain_requested = False    # operator-requested (node.drain)
        self.scrub_flagged = False
        self.dead = False
        self.dead_since = 0.0
        self._suspect_until = 0.0
        self._last_reasons: tuple[str, ...] = ()

    def observe(
        self,
        now: float,
        io_errors: int = 0,
        request_errors: int = 0,
        lame_duck: bool = False,
        draining: bool = False,
    ) -> None:
        self.detector.observe(now)
        # per-beat error delta: an EIO on the serving path bumps BOTH
        # counters (io_errors at the watchdog, request_errors from its
        # 500 reply), so summing would double-count disk errors and
        # trip the EWMA threshold at half the documented sensitivity —
        # max() gives the true count when they overlap and still
        # catches the disjoint cases (scrub-path EIOs produce no 500;
        # handler bugs 500 with no disk fault). Cumulative counters:
        # a restarted node resets to 0 — clamp so the reset never
        # reads as a negative burst.
        io_delta = max(0, io_errors - self._last_io_errors)
        req_delta = max(0, request_errors - self._last_request_errors)
        delta = max(io_delta, req_delta)
        self._last_io_errors = io_errors
        self._last_request_errors = request_errors
        a = self._EWMA_ALPHA
        self.err_ewma = a * delta + (1 - a) * self.err_ewma
        self.lame_duck = lame_duck
        self.draining = draining
        self.dead = False

    def suspicion_reasons(self, now: float) -> tuple[str, ...]:
        """Why this node is currently suspect; empty = clean signals."""
        reasons = []
        phi = self.detector.phi(now)
        if phi > phi_threshold():
            reasons.append("phi=%.1f" % phi)
        if self.err_ewma > err_ewma_threshold():
            reasons.append("err_ewma=%.1f" % self.err_ewma)
        if self.scrub_flagged:
            reasons.append("scrub")
        return tuple(reasons)

    def state(self, now: float | None = None) -> str:
        """healthy | suspect | dead, with hysteresis: suspicion holds
        for recover_s past the last bad signal so a flapping gray node
        doesn't flap the pool."""
        if not enabled():
            return DEAD if self.dead else HEALTHY
        if self.dead:
            return DEAD
        now = time.monotonic() if now is None else now
        reasons = self.suspicion_reasons(now)
        if reasons:
            self._last_reasons = reasons
            self._suspect_until = now + recover_s()
            return SUSPECT
        if now < self._suspect_until:
            return SUSPECT
        return HEALTHY

    def assignable(self, now: float | None = None) -> bool:
        """May pick_for_write target this node? Suspects, lame ducks
        and draining nodes are all out; with the plane disabled only
        dead nodes are (the pre-health contract)."""
        if not enabled():
            return not self.dead
        if self.lame_duck or self.draining or self.drain_requested:
            return False
        return self.state(now) == HEALTHY

    def read_demoted(self, now: float | None = None) -> bool:
        """Order this replica LAST for reads? Only genuine suspicion
        demotes reads — a lame-duck or draining node still serves GETs
        fine and must keep taking them while its data moves off."""
        if not enabled():
            return False
        return self.state(now) != HEALTHY

    def score(self, now: float | None = None) -> float:
        """A single scalar for operator surfaces: max of the normalized
        signals (1.0 = at threshold)."""
        now = time.monotonic() if now is None else now
        s = max(
            self.detector.phi(now) / max(phi_threshold(), 1e-9),
            self.err_ewma / max(err_ewma_threshold(), 1e-9),
        )
        return round(s, 3)


class HealthPlane:
    """The master's per-node health registry. All mutation happens on
    the heartbeat/sweep paths (under the master's node lock); reads are
    lock-free dict probes + pure functions of (record, now)."""

    def __init__(self) -> None:
        self.nodes: dict[str, NodeHealth] = {}
        self._lock = threading.Lock()

    def _get(self, url: str) -> NodeHealth:
        rec = self.nodes.get(url)
        if rec is None:
            with self._lock:
                rec = self.nodes.setdefault(url, NodeHealth(url))
        return rec

    # -- signal ingestion --------------------------------------------------
    def observe_heartbeat(self, url: str, req) -> None:
        """One beat arrived: feed arrival time + the node's counters
        and self-reported flags (master Heartbeat handler)."""
        rec = self._get(url)
        was = rec.state()
        rec.observe(
            time.monotonic(),
            io_errors=getattr(req, "io_errors", 0),
            request_errors=getattr(req, "request_errors", 0),
            lame_duck=getattr(req, "lame_duck", False),
            draining=getattr(req, "draining", False),
        )
        self._note_transition(rec, was)

    def observe_scrub(self, url: str, flagged: bool) -> None:
        """Disk-health signal from scrub strikes: the node's heartbeat
        scrub rows currently report corruption or quarantined shards."""
        rec = self._get(url)
        was = rec.state()
        rec.scrub_flagged = flagged
        self._note_transition(rec, was)

    # dead records linger this long for operator surfaces, then prune
    # (an autoscaled fleet would otherwise grow self.nodes unbounded)
    DEAD_TTL_S = 3600.0

    def note_dead(self, url: str) -> None:
        """Heartbeat stream teardown or liveness sweep declared the
        node gone; a later re-register revives it via observe()."""
        rec = self.nodes.get(url)
        if rec is not None:
            was = rec.state()
            rec.dead = True
            rec.dead_since = time.monotonic()
            self._note_transition(rec, was)
        self._prune(time.monotonic())

    def _prune(self, now: float) -> None:
        """Drop records dead past DEAD_TTL_S (decommissioned hosts)."""
        stale = [
            url
            for url, rec in list(self.nodes.items())
            if rec.dead and now - rec.dead_since > self.DEAD_TTL_S
        ]
        if stale:
            with self._lock:
                for url in stale:
                    rec = self.nodes.get(url)
                    if rec is not None and rec.dead:
                        del self.nodes[url]

    def request_drain(self, url: str, stop: bool = False) -> None:
        """Operator drain intent (node.drain): excluded from assignment
        and the RepairScheduler moves its data off."""
        self._get(url).drain_requested = not stop

    def draining_urls(self) -> set[str]:
        return {
            url
            for url, rec in list(self.nodes.items())
            if (rec.drain_requested or rec.draining) and not rec.dead
        }

    def _note_transition(self, rec: NodeHealth, was: str) -> None:
        nowst = rec.state()
        if nowst != was:
            from seaweedfs_tpu.stats.metrics import HEALTH_TRANSITIONS

            HEALTH_TRANSITIONS.labels(nowst).inc()
            from seaweedfs_tpu.util import wlog

            wlog.warning(
                "health: node %s %s -> %s%s",
                rec.url, was, nowst,
                (" (%s)" % ", ".join(rec._last_reasons))
                if nowst == SUSPECT and rec._last_reasons else "",
            )

    # -- verdicts ----------------------------------------------------------
    def state(self, url: str) -> str:
        rec = self.nodes.get(url)
        return HEALTHY if rec is None else rec.state()

    def assignable(self, url: str) -> bool:
        rec = self.nodes.get(url)
        return True if rec is None else rec.assignable()

    def suspect(self, url: str) -> bool:
        """Demote this replica for reads / hedge eagerly against it?"""
        rec = self.nodes.get(url)
        return False if rec is None else rec.read_demoted()

    def order_nodes(self, nodes: list) -> list:
        """Stable-partition read candidates: non-demoted first. The
        cluster-wide twin of the client breaker's _partition_healthy —
        every client of this master sees suspects last without having
        to burn its own timeout learning it."""
        if not enabled() or len(nodes) < 2:
            return nodes
        now = time.monotonic()

        def demoted(dn) -> bool:
            rec = self.nodes.get(dn.url)
            return rec is not None and rec.read_demoted(now)

        good = [dn for dn in nodes if not demoted(dn)]
        if not good or len(good) == len(nodes):
            return nodes
        return good + [dn for dn in nodes if demoted(dn)]

    # -- operator surface --------------------------------------------------
    def payload(self) -> dict:
        """Per-node score/state/signal rows for /cluster/health."""
        now = time.monotonic()
        self._prune(now)
        rows = {}
        for url, rec in sorted(self.nodes.items()):
            rows[url] = {
                "State": rec.state(now),
                "Score": rec.score(now),
                "Phi": round(rec.detector.phi(now), 2),
                # detector readiness + learned detection horizon: rigs
                # and runbooks barrier/bound on THESE, never on the
                # configured heartbeat interval (docs/ANALYSIS.md v4,
                # the gray-failure deflake)
                "Warmed": rec.detector.warmed(),
                "GateS": round(rec.detector.gate_s(), 3),
                "ErrEwma": round(rec.err_ewma, 2),
                "LameDuck": rec.lame_duck,
                "Draining": rec.draining or rec.drain_requested,
                "ScrubFlagged": rec.scrub_flagged,
                "Reasons": list(rec.suspicion_reasons(now)),
            }
        return {
            "Enabled": enabled(),
            "PhiThreshold": phi_threshold(),
            "Nodes": rows,
        }


class DiskWatchdog:
    """Volume-server-local graceful degradation: repeated EIO/ENOSPC on
    the serving path flip the node into read-only lame-duck mode —
    announced on the next heartbeat (lame_duck field) so the master
    stops assigning writes here, and enforced locally (POST/DELETE
    shed with 503) so in-flight clients fail over instead of grinding
    against a dying disk.

    Strikes decay: `strikes` IO errors within `window_s` trip it
    (WEED_LAMEDUCK_ERRS / WEED_LAMEDUCK_WINDOW_S). Tripping is sticky
    until an operator restarts the process — a disk that threw EIO
    three times is not healed by the passage of time."""

    def __init__(self, strikes: int | None = None, window_s: float | None = None):
        if strikes is None:
            try:
                strikes = int(os.environ.get("WEED_LAMEDUCK_ERRS", "3"))
            except ValueError:
                strikes = 3
        if window_s is None:
            try:
                window_s = float(os.environ.get("WEED_LAMEDUCK_WINDOW_S", "60"))
            except ValueError:
                window_s = 60.0
        self.strikes = max(1, strikes)
        self.window_s = window_s
        self.io_errors = 0  # cumulative, rides the heartbeat
        self.lame_duck = False
        self._recent: list[float] = []
        self._lock = threading.Lock()
        self.on_trip = None  # callback (e.g. force a heartbeat NOW)

    def note_io_error(self, exc: BaseException | None = None) -> bool:
        """Record one failure if it is disk-class (EIO/ENOSPC/EDQUOT);
        returns True when it was counted — False means "not a disk
        fault, handle it your usual way" (a DeadlineExceeded or a
        connection error must never strike the disk)."""
        import errno as _errno

        if exc is not None:
            eno = getattr(exc, "errno", None)
            if eno not in (_errno.EIO, _errno.ENOSPC, _errno.EDQUOT):
                return False
        now = time.monotonic()
        tripped = False
        with self._lock:
            self.io_errors += 1
            self._recent = [
                t for t in self._recent if now - t <= self.window_s
            ]
            self._recent.append(now)
            if not self.lame_duck and len(self._recent) >= self.strikes:
                self.lame_duck = True
                tripped = True
        if tripped:
            from seaweedfs_tpu.util import wlog

            wlog.error(
                "health: %d IO errors within %.0fs — entering read-only "
                "lame-duck mode (writes shed with 503; restart to clear)",
                len(self._recent), self.window_s,
            )
            cb = self.on_trip
            if cb is not None:
                cb()
        return True
