"""HA master tier: compact raft consensus (reference raft_server.go)
plus the weedguard node-health plane (cluster/health.py,
docs/HEALTH.md)."""

from seaweedfs_tpu.cluster.raft import RaftNode  # noqa: F401
