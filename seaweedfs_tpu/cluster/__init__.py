"""HA master tier: compact raft consensus (reference raft_server.go)."""

from seaweedfs_tpu.cluster.raft import RaftNode  # noqa: F401
