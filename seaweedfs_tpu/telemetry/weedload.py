"""weedload: multi-PROCESS closed-loop load harness.

The in-process http tracker (bench.py `http`, BENCH_r06 caveat) shares
the GIL with the servers it measures — it cannot see cross-process
tail latency, which is exactly where the ROADMAP tail-latency work
lives. weedload runs every worker as its own OS process against a real
cluster over real sockets and reports p50/p99/p99.9 from log-bucketed
histograms, so it is the measurement substrate for hedging/admission
experiments.

Coordinated-omission safety: each worker is closed-loop (next request
issues only after the previous completes) but paces against a fixed
schedule when `rate` is set — latency is measured from the request's
SCHEDULED start, not its actual send. A server stall therefore charges
every request queued behind it with the stall time, instead of the
classic closed-loop lie where a 1 s freeze records one slow request
and silently omits the 999 that never got sent. `rate=0` degrades to
plain closed-loop (latency = send→reply) for max-throughput probes.

Workloads: `put` workers drive the full user write path (master
/dir/assign + volume POST per op); `get` workers read a pre-seeded
keyset (volume GET per op, round-robin). Histograms are log-bucketed
(~19% bucket growth from 50 µs to ~100 s) and merged in the parent;
quantiles come from the shared stats/quantile estimator so weedload,
the telemetry rings, and bench agree about tails by construction.
"""

from __future__ import annotations

import bisect
import http.client
import json
import multiprocessing
import time
import urllib.error
import urllib.request

from seaweedfs_tpu.stats.quantile import histogram_quantile

# ~4 buckets per octave: 50 us .. ~104 s in 89 bounds (+1 overflow)
_BOUNDS = tuple(5e-5 * 2 ** (i / 4) for i in range(85))


class LogHistogram:
    """Fixed log-bucketed latency histogram; cheap to record, merge,
    and ship over a multiprocessing queue as a plain list."""

    __slots__ = ("counts", "total", "sum", "max")

    def __init__(self, counts: list[int] | None = None):
        self.counts = counts or [0] * (len(_BOUNDS) + 1)
        self.total = sum(self.counts)
        self.sum = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        self.counts[bisect.bisect_left(_BOUNDS, seconds)] += 1
        self.total += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def merge(self, other: "LogHistogram") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.total += other.total
        self.sum += other.sum
        if other.max > self.max:
            self.max = other.max

    def quantile(self, q: float) -> float:
        if self.total == 0:
            return 0.0
        est = histogram_quantile(list(_BOUNDS), self.counts, q)
        # bucket interpolation can overshoot the true extreme by up to
        # one bucket width; the recorded max is a hard ceiling
        return min(est, self.max) if self.max > 0 else est

    def to_row(self) -> dict:
        return {
            "counts": self.counts,
            "sum": self.sum,
            "max": self.max,
        }

    @classmethod
    def from_row(cls, row: dict) -> "LogHistogram":
        h = cls(list(row["counts"]))
        h.sum = row["sum"]
        h.max = row["max"]
        return h


# ----------------------------------------------------------------------
# worker process


def _http(conns: dict, netloc: str, method: str, path: str,
          body: bytes | None = None, timeout: float = 30.0):
    """One request over a cached keep-alive connection; one fresh-dial
    retry on a torn connection (server restart, idle close)."""
    for attempt in (0, 1):
        conn = conns.get(netloc)
        if conn is None:
            conn = conns[netloc] = http.client.HTTPConnection(
                netloc, timeout=timeout
            )
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data
        except (OSError, http.client.HTTPException):
            conn.close()
            conns.pop(netloc, None)
            if attempt:
                raise
    raise RuntimeError("unreachable")


class _Shed(Exception):
    """A 503 from admission control: counted separately from errors —
    the request was deliberately refused, not failed."""


def _worker(spec: dict, out_q, barrier=None) -> None:
    """One load worker (runs in its own process). `spec`:
    mode ('put' | 'get' | 'mixed'), master, duration_s, payload, rate,
    keys, index, hedge. `barrier` (shared with the parent and every
    sibling) gates the measured loop until ALL workers finish their
    process bootstrap — a sibling still importing heavyweight modules
    pins the CPU and would charge multi-hundred-ms stalls to the
    server under test.

    `mixed` alternates one PUT then one GET per scheduled slot (the
    multi-tenant contention shape: writers and readers fight for the
    same disks). `hedge` (with keys entries carrying a replica url
    LIST) routes GETs through the qos hedged-read driver and reports
    fired/won/cancelled counts. A 503 reply counts as `shed`, not an
    error, and its latency lands in a separate histogram so the
    accepted-request quantiles stay honest under admission control."""
    mode = spec["mode"]
    master = spec["master"]
    payload = spec["payload"]
    rate = spec["rate"]
    keys = spec.get("keys") or []
    # degraded-GET worker knob (docs/SCRUB.md): a degraded read that
    # "succeeds" with truncated or zero-filled bytes is the worst
    # failure mode a latency number can hide — verify_bytes makes a
    # wrong-length body an ERROR, so the degraded A/B's `errors: 0`
    # actually certifies reconstruction, not just status codes
    verify_bytes = int(spec.get("verify_bytes") or 0)
    use_hedge = bool(spec.get("hedge"))
    hedge_stats: dict = {}
    if use_hedge:
        from seaweedfs_tpu.qos import hedge as _hedge
    if barrier is not None:
        barrier.wait(120)
    conns: dict[str, http.client.HTTPConnection] = {}
    hist = LogHistogram()
    shed_hist = LogHistogram()
    ops = 0
    errors = 0
    shed = 0
    err_samples: list[str] = []
    nbytes = 0
    interval = (1.0 / rate) if rate > 0 else 0.0
    start = time.perf_counter()
    deadline = start + spec["duration_s"]
    scheduled = start
    ki = spec.get("index", 0)  # stagger the round-robin start per worker

    def one_put():
        nonlocal nbytes
        status, data = _http(conns, master, "GET", "/dir/assign", timeout=30.0)
        if status != 200:
            raise RuntimeError(f"assign HTTP {status}")
        a = json.loads(data)
        if "error" in a:
            raise RuntimeError(f"assign: {a['error']}")
        status, data = _http(conns, a["url"], "POST", f"/{a['fid']}", payload)
        if status == 503:
            raise _Shed()
        if status not in (200, 201):
            raise RuntimeError(f"put HTTP {status}")
        nbytes += len(payload)

    def one_get():
        nonlocal nbytes, ki
        fid, loc = keys[ki % len(keys)]
        ki += 1
        urls = [loc] if isinstance(loc, str) else list(loc)
        if use_hedge and len(urls) > 1:
            # rotate the primary across replicas so the hedged arm's
            # first attempt hits the slow replica as often as the
            # unhedged arm does — the A/B measures hedging, not luck
            r = ki % len(urls)
            cand = [f"{urls[(r + j) % len(urls)]}/{fid}" for j in range(len(urls))]
            data, _ = _hedge.download(
                cand, key=fid.partition(",")[0], stats=hedge_stats
            )
            nbytes += len(data)
            return
        url = urls[ki % len(urls)]
        status, data = _http(conns, url, "GET", f"/{fid}")
        if status == 503:
            raise _Shed()
        if status != 200:
            raise RuntimeError(f"get {fid} HTTP {status}")
        if verify_bytes and len(data) != verify_bytes:
            raise RuntimeError(
                f"get {fid}: {len(data)} bytes, expected {verify_bytes} "
                f"(degraded reconstruction served wrong-length body)"
            )
        nbytes += len(data)

    n_slot = 0
    while True:
        now = time.perf_counter()
        if interval:
            if scheduled > now:
                time.sleep(scheduled - now)
            t_ref = scheduled  # CO correction: charge from the schedule
            scheduled += interval
        else:
            t_ref = now
        if t_ref >= deadline or now >= deadline:
            break
        n_slot += 1
        try:
            if mode == "put" or (mode == "mixed" and n_slot % 2):
                one_put()
            else:
                one_get()
        except _Shed:
            shed += 1
            shed_hist.record(time.perf_counter() - t_ref)
            continue
        except Exception as e:  # noqa: BLE001 — counted, not fatal
            errors += 1
            if len(err_samples) < 5:
                err_samples.append(repr(e)[:200])
            hist.record(time.perf_counter() - t_ref)
            continue
        hist.record(time.perf_counter() - t_ref)
        ops += 1
    for c in conns.values():
        c.close()
    out_q.put({
        "mode": mode,
        "ops": ops,
        "errors": errors,
        "shed": shed,
        "err_samples": err_samples,
        "bytes": nbytes,
        "hist": hist.to_row(),
        "shed_hist": shed_hist.to_row(),
        "hedge": hedge_stats,
        "wall_s": time.perf_counter() - start,
    })


# ----------------------------------------------------------------------
# parent


def seed_keys(
    master: str,
    n: int,
    payload: bytes,
    etags: dict | None = None,
    content_type: str = "application/octet-stream",
) -> list[tuple[str, str]]:
    """Write n blobs for the GET workers to hammer; returns (fid, url).
    Pass `etags` (a dict) to also capture each upload's ETag — the
    validators the conditional-GET mix revalidates against. The default
    octet-stream content type stores no mime flag (urllib's implicit
    x-www-form-urlencoded would); pass e.g. "image/png" to seed
    FLAGGED needles for the pre-rendered-header fast-path mix. Beware
    text/* and json/xml types: the write path gzips those uploads
    transparently, and gzipped needles sit OFF the C fast path."""
    keys: list[tuple[str, str]] = []
    for _ in range(n):
        with urllib.request.urlopen(
            f"http://{master}/dir/assign", timeout=10
        ) as r:
            a = json.loads(r.read())
        if "error" in a:
            raise RuntimeError(f"seed assign: {a['error']}")
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}", data=payload, method="POST",
            headers={"Content-Type": content_type},
        )
        # an admission-armed server sheds seed writes once the cold
        # burst drains — honor its Retry-After instead of dying
        for attempt in range(20):
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    if etags is not None:
                        etags[a["fid"]] = json.loads(r.read()).get(
                            "eTag", ""
                        )
                break
            except urllib.error.HTTPError as e:
                if e.code != 503 or attempt == 19:
                    raise
                try:
                    delay = float(e.headers.get("Retry-After", "0.5"))
                except (TypeError, ValueError):
                    delay = 0.5
                time.sleep(min(max(delay, 0.05), 2.0))
        keys.append((a["fid"], a["url"]))
    return keys


def seed_keys_replicated(
    master: str, n: int, payload: bytes, replication: str = "010"
) -> list[tuple[str, list[str]]]:
    """Seed n blobs onto REPLICATED volumes and return every replica:
    (fid, [url, ...]) rows — the keyset shape the hedged-GET workers
    (and the slow-replica A/B) need. The POST fans out to the replicas
    server-side; /dir/lookup reports where the copies live."""
    keys: list[tuple[str, list[str]]] = []
    for _ in range(n):
        with urllib.request.urlopen(
            f"http://{master}/dir/assign?replication={replication}",
            timeout=10,
        ) as r:
            a = json.loads(r.read())
        if "error" in a:
            raise RuntimeError(f"seed assign: {a['error']}")
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{a['url']}/{a['fid']}", data=payload, method="POST",
                headers={"Content-Type": "application/octet-stream"},
            ),
            timeout=10,
        ).close()
        vid = a["fid"].partition(",")[0]
        with urllib.request.urlopen(
            f"http://{master}/dir/lookup?volumeId={vid}", timeout=10
        ) as r:
            lk = json.loads(r.read())
        urls = [loc["url"] for loc in lk.get("locations", [])] or [a["url"]]
        keys.append((a["fid"], urls))
    return keys


def _get_fan_worker(spec: dict, out_q, barrier=None) -> None:
    """One GET *fan* worker: K nonblocking keep-alive connections
    driven by a single selector loop in this process — the client-side
    shape for connection-scale serving benches (256+ concurrent
    connections across a few processes, where thread-per-connection
    clients would measure their own scheduler instead of the server).

    Each connection is closed-loop (next GET only after the previous
    response drains). With `rate` set, each connection paces against
    its own fixed schedule and latency is charged from the SCHEDULED
    send — the same coordinated-omission discipline as `_worker`. A
    `range_every` of N makes every Nth request on a connection carry a
    Range header cycling through `ranges` (mixed 200/206 traffic).

    A 503 (admission-control shed, docs/QOS.md) is counted as `shed`
    and the connection HONORS the server's Retry-After before its next
    attempt — the same contract op.http_call implements — so the
    admission A/B measures the designed backpressure loop, not a
    client that spams the server it was just refused by.

    A `cond_every` of N makes every Nth request on a connection carry
    an If-None-Match with the blob's real ETag (from `etags`): the
    conditional-GET mix, where the server revalidates with a 304 out
    of the C fast path instead of moving the body. 304s count as
    successful ops and separately as `not_modified`.

    spec: mode='get_fan', duration_s, keys, conns, rate, index,
    range_every, ranges, cond_every, etags."""
    import selectors
    import socket as _socket

    if barrier is not None:
        barrier.wait(120)

    keys = spec["keys"]
    duration = spec["duration_s"]
    rate = spec["rate"]
    nconns = spec["conns"]
    range_every = spec.get("range_every", 0)
    ranges = spec.get("ranges") or ["bytes=0-127"]
    cond_every = spec.get("cond_every", 0)
    etags = spec.get("etags") or {}
    interval = (1.0 / rate) if rate > 0 else 0.0
    hist = LogHistogram()
    shed_hist = LogHistogram()
    ops = errors = nbytes = shed = not_modified = 0
    err_samples: list[str] = []
    sel = selectors.DefaultSelector()
    start = time.perf_counter()
    deadline = start + duration

    class _Conn:
        __slots__ = ("sock", "buf", "need", "t_ref", "scheduled", "ki",
                     "nreq", "netloc", "inflight", "resume")

    def _dial(netloc: str):
        host, _, port = netloc.partition(":")
        s = _socket.create_connection((host, int(port)), timeout=30)
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, True)
        s.setblocking(False)
        return s

    def _send(c, now: float) -> None:
        fid, url = keys[c.ki % len(keys)]
        c.ki += nconns  # stride: fan the keyset across the conns
        c.nreq += 1
        hdr = b""
        if range_every and c.nreq % range_every == 0:
            hdr = b"Range: " + ranges[c.nreq % len(ranges)].encode() + b"\r\n"
        if cond_every and c.nreq % cond_every == 0:
            etag = etags.get(fid, "")
            if etag:
                hdr += (
                    b'If-None-Match: "' + etag.encode() + b'"\r\n'
                )
        req = b"GET /" + fid.encode() + b" HTTP/1.1\r\n" + hdr + b"\r\n"
        c.t_ref = c.scheduled if interval else now
        c.buf = b""
        c.need = -1
        c.inflight = True
        try:
            # a ~60B request always fits an empty send buffer, and the
            # closed loop guarantees the buffer IS empty here
            c.sock.sendall(req)
        except OSError:
            pass  # the read side sees the teardown and redials

    def _complete(c, now: float) -> bool:
        """True once the buffered bytes hold one whole response."""
        if c.need < 0:
            end = c.buf.find(b"\r\n\r\n")
            if end < 0:
                return False
            cl = 0
            for line in c.buf[:end].split(b"\r\n")[1:]:
                k, _, v = line.partition(b":")
                if k.strip().lower() == b"content-length":
                    cl = int(v.strip())
            c.need = end + 4 + cl
        return len(c.buf) >= c.need

    conns: list = []
    try:
        for i in range(nconns):
            c = _Conn()
            c.netloc = keys[(spec.get("index", 0) + i) % len(keys)][1]
            c.sock = _dial(c.netloc)
            c.ki = spec.get("index", 0) + i
            c.nreq = i  # desync the Range cadence across conns
            c.buf = b""
            c.need = -1
            c.inflight = False
            c.resume = 0.0
            # stagger schedules so paced conns don't phase-lock
            c.scheduled = start + (interval * i / nconns if interval else 0.0)
            sel.register(c.sock, selectors.EVENT_READ, c)
            conns.append(c)
        now = time.perf_counter()
        for c in conns:
            if not interval or c.scheduled <= now:
                _send(c, now)
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            events = sel.select(timeout=0.05)
            now = time.perf_counter()
            for key, _mask in events:
                c = key.data
                try:
                    chunk = c.sock.recv(1 << 18)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError as e:
                    chunk = b""
                    if len(err_samples) < 5:
                        err_samples.append(repr(e)[:200])
                if not chunk:
                    # torn connection: count the in-flight op lost,
                    # then redial so concurrency holds
                    if c.inflight:
                        errors += 1
                        hist.record(now - c.t_ref)
                    sel.unregister(c.sock)
                    c.sock.close()
                    try:
                        c.sock = _dial(c.netloc)
                    except OSError:
                        continue  # server gone: this conn retires
                    sel.register(c.sock, selectors.EVENT_READ, c)
                    c.inflight = False
                    c.buf = b""
                    c.need = -1
                    if not interval:
                        _send(c, now)
                    continue
                c.buf += chunk
                if c.inflight and _complete(c, now):
                    status = c.buf[9:12]
                    if status in (b"200", b"206", b"304"):
                        ops += 1
                        nbytes += c.need
                        if status == b"304":
                            not_modified += 1
                        hist.record(now - c.t_ref)
                    elif status == b"503":
                        # admission-control shed (docs/QOS.md): refused
                        # by design, histogrammed apart so accepted-
                        # request quantiles stay honest; honor the
                        # server's Retry-After before this connection's
                        # next attempt
                        shed += 1
                        shed_hist.record(now - c.t_ref)
                        head = c.buf[: c.need].lower()
                        backoff = 0.5
                        idx = head.find(b"retry-after:")
                        if idx >= 0:
                            tok = head[idx + 12 : idx + 28].split(b"\r", 1)[0]
                            try:
                                backoff = float(tok.strip())
                            except ValueError:
                                pass
                        c.resume = now + min(max(backoff, 0.05), 1.0)
                    else:
                        errors += 1
                        if len(err_samples) < 5:
                            err_samples.append(
                                c.buf[:80].decode("latin-1", "replace")
                            )
                        hist.record(now - c.t_ref)
                    c.buf = c.buf[c.need :]
                    c.need = -1
                    c.inflight = False
                    if interval:
                        c.scheduled += interval
                        if c.scheduled <= now and c.resume <= now:
                            _send(c, now)  # behind schedule: CO charge
                    elif c.resume <= now:
                        _send(c, now)
            if interval:
                for c in conns:
                    if (
                        not c.inflight
                        and c.scheduled <= now
                        and c.resume <= now
                    ):
                        _send(c, now)
            else:
                # shed-backoff wakeups: a connection honoring a
                # Retry-After re-enters the closed loop here
                for c in conns:
                    if not c.inflight and c.resume and c.resume <= now:
                        c.resume = 0.0
                        _send(c, now)
    finally:
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        sel.close()
    out_q.put({
        "mode": "get",
        "ops": ops,
        "errors": errors,
        "shed": shed,
        "not_modified": not_modified,
        "err_samples": err_samples,
        "bytes": nbytes,
        "hist": hist.to_row(),
        "shed_hist": shed_hist.to_row(),
        "wall_s": time.perf_counter() - start,
    })


def _scrape_serve_stats(urls: set[str]) -> dict:
    """Sum the C fast-path counters (/status ServeStats) across the
    distinct volume servers in `urls`; {} when none answer."""
    total: dict = {}
    for url in urls:
        try:
            with urllib.request.urlopen(f"http://{url}/status", timeout=5) as r:
                stats = json.loads(r.read()).get("ServeStats") or {}
        except (OSError, ValueError):
            continue
        for k, v in stats.items():
            if isinstance(v, (int, float)):
                total[k] = total.get(k, 0) + v
    return total


def run_get_fan(
    master: str,
    duration_s: float = 10.0,
    processes: int = 4,
    conns_per_proc: int = 64,
    payload_bytes: int = 1024,
    rate: float = 0.0,
    seed_n: int = 64,
    range_every: int = 0,
    ranges: list[str] | None = None,
    cond_every: int = 0,
    keys: list[tuple[str, str]] | None = None,
    etags: dict | None = None,
    mp_start: str = "spawn",
) -> dict:
    """GET-heavy connection-scale load: `processes` × `conns_per_proc`
    keep-alive connections in closed loop against the cluster at
    `master`. `rate` is per-CONNECTION req/s (0 = unpaced
    max-throughput probe; >0 = coordinated-omission-safe pacing).
    `cond_every` = N sends every Nth request per connection as a
    conditional GET (If-None-Match with the seeded ETag → 304).
    Returns the same report shape as run_load (mode 'get'), plus
    `ratio_304` and a `fast_path` block (the served/handoff counter
    deltas scraped from each volume server's /status ServeStats)."""
    payload = (b"weedload\x00\xff" * ((payload_bytes // 10) + 1))[:payload_bytes]
    if keys is None:
        etags = {} if etags is None else etags
        keys = seed_keys(master, seed_n, payload, etags=etags)
    ctx = multiprocessing.get_context(mp_start)
    out_q = ctx.Queue()
    barrier = ctx.Barrier(processes)
    vol_urls = {url for _, url in keys}
    stats_before = _scrape_serve_stats(vol_urls)
    procs = []
    for i in range(processes):
        spec = {
            "mode": "get_fan",
            "duration_s": duration_s,
            "keys": keys,
            "conns": conns_per_proc,
            "rate": rate,
            "index": i * 13,
            "range_every": range_every,
            "ranges": ranges or [],
            "cond_every": cond_every,
            "etags": etags or {},
        }
        p = ctx.Process(
            target=_get_fan_worker, args=(spec, out_q, barrier), daemon=True
        )
        p.start()
        procs.append(p)
    import queue as _queue

    rows = []
    join_deadline = time.time() + duration_s + 90.0
    while len(rows) < len(procs) and time.time() < join_deadline:
        try:
            rows.append(out_q.get(timeout=1.0))
        except _queue.Empty:
            if any(not p.is_alive() and p.exitcode != 0 for p in procs):
                break
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()
    if len(rows) < len(procs):
        raise RuntimeError(
            f"weedload get_fan: only {len(rows)}/{len(procs)} workers "
            f"reported (exit codes {[p.exitcode for p in procs]})"
        )
    hist = LogHistogram()
    shed_hist = LogHistogram()
    ops = errors = nbytes = shed = not_modified = 0
    samples: list[str] = []
    for r in rows:
        hist.merge(LogHistogram.from_row(r["hist"]))
        if r.get("shed_hist"):
            shed_hist.merge(LogHistogram.from_row(r["shed_hist"]))
        ops += r["ops"]
        errors += r["errors"]
        shed += r.get("shed", 0)
        not_modified += r.get("not_modified", 0)
        nbytes += r["bytes"]
        samples.extend(r["err_samples"])
    wall = max(r["wall_s"] for r in rows)
    report = _summarize(hist, ops, errors, nbytes, wall)
    report["shed"] = shed
    if shed:
        report["shed_p99_ms"] = round(shed_hist.quantile(0.99) * 1e3, 3)
    report["not_modified"] = not_modified
    report["ratio_304"] = round(not_modified / ops, 4) if ops else 0.0
    # C fast-path accounting over the run: served/handoffs deltas from
    # every volume server the keyset touches (hit ratio = the fraction
    # of requests that never left the C loop)
    stats_after = _scrape_serve_stats(vol_urls)
    if stats_after:
        delta = {
            k: stats_after.get(k, 0) - stats_before.get(k, 0)
            for k in ("served", "not_modified", "cache_hits", "handoffs")
        }
        denom = delta["served"] + delta["handoffs"]
        delta["hit_ratio"] = (
            round(delta["served"] / denom, 4) if denom else 0.0
        )
        report["fast_path"] = delta
    report["err_samples"] = samples[:5]
    report["config"] = {
        "master": master,
        "duration_s": duration_s,
        "processes": processes,
        "conns_per_proc": conns_per_proc,
        "connections": processes * conns_per_proc,
        "payload_bytes": payload_bytes,
        "rate_per_conn": rate,
        "range_every": range_every,
        "cond_every": cond_every,
        "coordinated_omission_safe": rate > 0,
    }
    return report


def _summarize(hist: LogHistogram, ops: int, errors: int, nbytes: int,
               wall_s: float) -> dict:
    return {
        "ops": ops,
        "errors": errors,
        "req_per_sec": round(ops / wall_s, 2) if wall_s > 0 else 0.0,
        "mb_per_sec": round(nbytes / wall_s / 1e6, 3) if wall_s > 0 else 0.0,
        "p50_ms": round(hist.quantile(0.50) * 1e3, 3),
        "p99_ms": round(hist.quantile(0.99) * 1e3, 3),
        "p999_ms": round(hist.quantile(0.999) * 1e3, 3),
        "max_ms": round(hist.max * 1e3, 3),
        "mean_ms": round(hist.sum / hist.total * 1e3, 3) if hist.total else 0.0,
    }


def run_load(
    master: str,
    duration_s: float = 10.0,
    writers: int = 2,
    readers: int = 2,
    payload_bytes: int = 1024,
    rate: float = 0.0,
    seed_n: int = 64,
    mp_start: str = "spawn",
    mixed: int = 0,
    hedge: bool = False,
    keys: list | None = None,
    verify_bytes: int = 0,
) -> dict:
    """Drive writers+readers(+mixed) worker PROCESSES against the
    cluster at `master`; returns the merged report. `rate` is
    per-worker target req/s (0 = unpaced closed loop). `mp_start` picks
    the multiprocessing start method — spawn (default) never inherits
    the parent's threads/locks, which matters when the caller embeds
    in-process servers.

    QoS knobs (docs/QOS.md): `mixed` adds workers alternating PUT and
    GET (cross-plane contention in one closed loop); `hedge` routes
    GETs through the hedged-read driver — pass `keys` rows shaped
    (fid, [replica_url, ...]) (seed_keys_replicated builds them; a
    caller injecting a slow replica rewrites one url to its proxy).
    The report carries hedge fired/won/cancelled counts and `shed`
    (503-refused requests, histogrammed apart from accepted ones).

    `verify_bytes` (the degraded-GET worker, docs/SCRUB.md): GET bodies
    whose length differs are counted as errors — drives real degraded
    traffic against an EC volume with a DeadShard and certifies the
    reconstruction, not just the status code."""
    if writers <= 0 and readers <= 0 and mixed <= 0:
        raise ValueError("need at least one worker")
    # \x00\xff keeps the body ungzippable so the write path stays honest
    payload = (b"weedload\x00\xff" * ((payload_bytes // 10) + 1))[:payload_bytes]
    if keys is None:
        keys = (
            seed_keys(master, seed_n, payload)
            if readers > 0 or mixed > 0
            else []
        )
    ctx = multiprocessing.get_context(mp_start)
    out_q = ctx.Queue()
    n_workers = writers + readers + mixed
    barrier = ctx.Barrier(n_workers)
    procs = []
    for i in range(n_workers):
        spec = {
            "mode": (
                "put" if i < writers
                else "get" if i < writers + readers
                else "mixed"
            ),
            "master": master,
            "duration_s": duration_s,
            "payload": payload,
            "rate": rate,
            "keys": keys,
            "index": i * 7,
            "hedge": hedge,
            "verify_bytes": verify_bytes,
        }
        p = ctx.Process(
            target=_worker, args=(spec, out_q, barrier), daemon=True
        )
        p.start()
        procs.append(p)
    import queue as _queue

    rows = []
    join_deadline = time.time() + duration_s + 60.0
    while len(rows) < len(procs) and time.time() < join_deadline:
        try:
            rows.append(out_q.get(timeout=1.0))
        except _queue.Empty:
            # a worker that died before posting (OOM kill, spawn
            # bootstrap failure) must surface as a named error, not a
            # 60s hang ending in a raw queue.Empty
            dead = [
                p for p in procs if not p.is_alive() and p.exitcode != 0
            ]
            if dead and len(rows) + sum(1 for p in procs if p.is_alive()) < len(procs):
                break
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()
    if len(rows) < len(procs):
        codes = [p.exitcode for p in procs]
        raise RuntimeError(
            f"weedload: only {len(rows)}/{len(procs)} workers reported "
            f"(exit codes {codes}) — a worker died before posting results"
        )
    report: dict = {
        "config": {
            "master": master,
            "duration_s": duration_s,
            "writers": writers,
            "readers": readers,
            "mixed": mixed,
            "hedge": hedge,
            "payload_bytes": payload_bytes,
            "rate_per_worker": rate,
            "coordinated_omission_safe": rate > 0,
            "processes": len(procs),
        },
    }
    for mode in ("put", "get", "mixed"):
        mode_rows = [r for r in rows if r["mode"] == mode]
        if not mode_rows:
            continue
        hist = LogHistogram()
        shed_hist = LogHistogram()
        ops = errors = nbytes = shed = 0
        hedge_fired = hedge_won = hedge_cancelled = 0
        wall = 0.0
        samples: list[str] = []
        for r in mode_rows:
            hist.merge(LogHistogram.from_row(r["hist"]))
            if r.get("shed_hist"):
                shed_hist.merge(LogHistogram.from_row(r["shed_hist"]))
            ops += r["ops"]
            errors += r["errors"]
            shed += r.get("shed", 0)
            nbytes += r["bytes"]
            wall = max(wall, r["wall_s"])
            samples.extend(r["err_samples"])
            hstats = r.get("hedge") or {}
            hedge_fired += hstats.get("fired", 0)
            hedge_won += hstats.get("won", 0)
            hedge_cancelled += hstats.get("cancelled", 0)
        report[mode] = _summarize(hist, ops, errors, nbytes, wall)
        report[mode]["shed"] = shed
        if shed:
            report[mode]["shed_p99_ms"] = round(
                shed_hist.quantile(0.99) * 1e3, 3
            )
        if hedge:
            report[mode]["hedge_fired"] = hedge_fired
            report[mode]["hedge_won"] = hedge_won
            report[mode]["hedge_cancelled"] = hedge_cancelled
        if samples:
            report[mode]["err_samples"] = samples[:5]
    return report
