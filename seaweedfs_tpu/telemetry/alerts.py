"""SLO alert rules with firing→resolved transitions.

The collector evaluates a fixed rule set every scrape cycle and hands
this manager a list of *conditions* — (rule, target, active, value,
detail). The manager owns the state machine:

    ok → pending (condition active, younger than the rule's for_s)
       → firing  (condition held for for_s; logged, gauge set to 1)
       → resolved (condition cleared; logged, gauge back to 0,
                   appended to bounded history)

Only FIRING and the transitions are operator-visible; pending exists
so one slow scrape or one stray 500 doesn't flap an alert. Firing
alerts are re-exported as `weed_alert_firing{alert,target}` gauges so
any external scraper of the master inherits the cluster's alert state
for free (the reference pushes raw metrics and leaves alerting to
Prometheus; here the master IS the aggregator, so it must also be the
rule engine).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from seaweedfs_tpu.stats.metrics import ALERT_FIRING
from seaweedfs_tpu.util import wlog

_HISTORY_CAP = 128


@dataclass(frozen=True)
class AlertRule:
    name: str
    severity: str = "warning"  # warning | critical
    for_s: float = 0.0  # condition must hold this long before firing
    help: str = ""


@dataclass
class AlertState:
    rule: AlertRule
    target: str
    state: str = "pending"  # pending | firing
    since: float = field(default_factory=time.time)
    fired_at: float = 0.0
    value: float = 0.0
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "Alert": self.rule.name,
            "Severity": self.rule.severity,
            "Target": self.target,
            "State": self.state,
            "SinceUnix": round(self.since, 3),
            "FiredAtUnix": round(self.fired_at, 3),
            "Value": round(self.value, 6),
            "Detail": self.detail,
        }


class AlertManager:
    def __init__(self, on_fire=None):
        self._lock = threading.Lock()
        self._active: dict[tuple[str, str], AlertState] = {}
        self._history: list[dict] = []  # resolved alerts, newest last
        # called with each alert row on the pending→firing edge, AFTER
        # the state lock is released (the capsule coordinator captures
        # evidence from here; it must be free to read alert state)
        self.on_fire = on_fire

    def evaluate(
        self,
        conditions: list[tuple[AlertRule, str, bool, float, str]],
        now: float | None = None,
    ) -> None:
        """One evaluation cycle. `conditions` must carry EVERY rule ×
        target pair the caller checked this cycle — a pair absent from
        the list is treated as inactive (its alert resolves)."""
        now = time.time() if now is None else now
        seen: set[tuple[str, str]] = set()
        fired: list[dict] = []
        with self._lock:
            for rule, target, active, value, detail in conditions:
                key = (rule.name, target)
                seen.add(key)
                st = self._active.get(key)
                if active:
                    if st is None:
                        st = self._active[key] = AlertState(
                            rule, target, since=now
                        )
                    st.value, st.detail = value, detail
                    if st.state == "pending" and now - st.since >= rule.for_s:
                        st.state = "firing"
                        st.fired_at = now
                        ALERT_FIRING.set(1.0, rule.name, target)
                        wlog.warning(
                            "alert FIRING %s target=%s value=%.4g %s",
                            rule.name, target, value, detail,
                        )
                        fired.append(st.to_dict())
                else:
                    self._resolve(key, now)
            # rule×target pairs that vanished entirely (target forgotten)
            for key in [k for k in self._active if k not in seen]:
                self._resolve(key, now)
        if fired and self.on_fire is not None:
            for row in fired:
                try:
                    self.on_fire(row)
                except Exception as e:  # noqa: BLE001 — hook never breaks eval
                    wlog.warning("alert on_fire hook failed: %r", e)

    def _resolve(self, key: tuple[str, str], now: float) -> None:
        st = self._active.pop(key, None)
        if st is None:
            return
        # drop the row outright: a resolved alert for a forgotten target
        # must not linger as a 0-valued gauge on /metrics forever
        ALERT_FIRING.remove(st.rule.name, st.target)
        if st.state == "firing":
            wlog.info(
                "alert resolved %s target=%s after %.1fs",
                st.rule.name, st.target, now - st.fired_at,
            )
            row = st.to_dict()
            row["State"] = "resolved"
            row["ResolvedAtUnix"] = round(now, 3)
            self._history.append(row)
            del self._history[:-_HISTORY_CAP]

    # ------------------------------------------------------------------
    def firing(self) -> list[dict]:
        with self._lock:
            return [
                st.to_dict()
                for st in sorted(
                    self._active.values(),
                    key=lambda s: (s.rule.severity != "critical", s.since),
                )
                if st.state == "firing"
            ]

    def payload(self) -> dict:
        """/cluster/alerts body: firing + pending + resolved history."""
        with self._lock:
            active = sorted(
                self._active.values(),
                key=lambda s: (s.rule.severity != "critical", s.since),
            )
            return {
                "Firing": [
                    s.to_dict() for s in active if s.state == "firing"
                ],
                "Pending": [
                    s.to_dict() for s in active if s.state == "pending"
                ],
                "History": list(self._history[-32:]),
            }
