"""Continuous sampling profiler: folded stacks on every daemon.

Generalizes util/profiling.CpuProfile (one-shot, instrumenting, whole-
run) into an always-on statistical sampler cheap enough for production
serving (the <=1% bound is enforced by bench.py's `load` config): a
single background thread wakes every WEED_PROF_MS milliseconds, grabs
`sys._current_frames()` (one C call), walks each thread's frame chain,
and bumps a counter keyed by the stack tuple. No per-call hooks, no
sys.setprofile — the serving path is never instrumented, only observed
while the sampler briefly holds the GIL.

Cost engineering: frame-walk labels are interned per code object
(id(code) → "module:qualname" built once), so a tick is N_threads ×
stack_depth dict lookups plus one counter bump — single-digit
microseconds per thread at the default 10 ms period (~0.1% of one
core). The aggregate is a plain dict guarded by one lock taken per
tick and per snapshot, never on any request path.

Operator surface: every daemon serves `/debug/profile?seconds=S`
through the mini request loop (util/httpd._serve_debug): snapshot,
wait S seconds, diff — a flamegraph-ready folded-stack view of exactly
that window. `?fmt=folded` emits flamegraph.pl input; default JSON.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_ENABLED = os.environ.get("WEED_PROF", "1") != "0"
try:
    _INTERVAL_S = max(1.0, float(os.environ.get("WEED_PROF_MS", "10") or 10)) / 1000.0
except ValueError:
    # a malformed tuning knob must never keep a serving daemon from
    # booting (every daemon's start() imports this module)
    _INTERVAL_S = 0.010

# sampling state: one process-wide sampler, started by every daemon's
# start() (idempotent) so workers and all-in-one towers share it
_lock = threading.Lock()
_counts: dict[tuple[str, ...], int] = {}
_samples = 0
_started = False
_paused = False
_started_at = 0.0
_label_cache: dict[int, str] = {}


def _label(frame) -> str:
    code = frame.f_code
    lab = _label_cache.get(id(code))
    if lab is None:
        mod = frame.f_globals.get("__name__", "?")
        lab = _label_cache[id(code)] = f"{mod}.{code.co_name}"
        if len(_label_cache) > 65536:
            # id() reuse after code-object churn could alias labels;
            # cap the cache instead of letting it grow forever
            _label_cache.clear()
            _label_cache[id(code)] = lab
    return lab


def _sample_loop() -> None:
    global _samples
    me = threading.get_ident()
    while True:
        time.sleep(_INTERVAL_S)
        if _paused:
            continue
        frames = sys._current_frames()
        ticks: list[tuple[str, ...]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            stack: list[str] = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                stack.append(_label(f))
                f = f.f_back
                depth += 1
            stack.reverse()  # outermost first: flamegraph fold order
            ticks.append(tuple(stack))
        del frames
        with _lock:
            _samples += 1
            for key in ticks:
                _counts[key] = _counts.get(key, 0) + 1


def ensure_started() -> bool:
    """Start the process-wide sampler (idempotent). Every daemon's
    start() calls this; WEED_PROF=0 keeps the process sampler-free."""
    global _started, _started_at
    if not _ENABLED:
        return False
    with _lock:
        if _started:
            return True
        _started = True
        _started_at = time.time()
    threading.Thread(
        target=_sample_loop, daemon=True, name="prof-sampler"
    ).start()
    return True


def set_paused(paused: bool) -> None:
    """bench A/B seam: stop sampling without killing the thread."""
    global _paused
    _paused = bool(paused)


def running() -> bool:
    return _started and not _paused


def snapshot() -> tuple[int, dict[tuple[str, ...], int]]:
    with _lock:
        return _samples, dict(_counts)


def capture(seconds: float) -> dict:
    """Folded-stack aggregate over the NEXT `seconds` (snapshot → wait
    → diff). seconds <= 0 returns the since-start aggregate. The wait
    parks only the calling (operator request) thread."""
    if not _started:
        ensure_started()
    if seconds > 0:
        s0, c0 = snapshot()
        # hot-loop exemption (analysis/hotloop._EXEMPT_QUALS): this
        # sleep parks only the requesting operator connection's thread
        # for the capped capture window — it IS the capture
        time.sleep(min(seconds, 60.0))
        s1, c1 = snapshot()
        samples = s1 - s0
        window = {
            k: n - c0.get(k, 0) for k, n in c1.items() if n - c0.get(k, 0) > 0
        }
        span = seconds
    else:
        samples, window = snapshot()
        span = time.time() - _started_at if _started_at else 0.0
    return {
        "enabled": _ENABLED,
        "running": running(),
        "interval_ms": _INTERVAL_S * 1000.0,
        "seconds": round(span, 3),
        "samples": samples,
        "stacks": {";".join(k): n for k, n in window.items()},
    }


def render_folded(payload: dict) -> str:
    """flamegraph.pl-ready text: `a;b;c N` per line, hottest first."""
    stacks = payload.get("stacks", {})
    lines = [
        f"{stack} {n}"
        for stack, n in sorted(stacks.items(), key=lambda kv: -kv[1])
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def reset() -> None:
    """Test hook: clear aggregates (the thread keeps running)."""
    global _samples
    with _lock:
        _counts.clear()
        _samples = 0
