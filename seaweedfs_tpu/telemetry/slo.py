"""SLO burn-rate engine (weedscope, docs/TELEMETRY.md).

Declarative objectives — per-daemon-kind availability and latency
targets, per plane (serve|scrub|repair|tier) — evaluated every
collector cycle against the ring TSDB, with MULTI-WINDOW MULTI-BURN
alerting (the SRE-workbook shape, scaled to this tree's timescales):

    burn = bad_fraction / (1 - target)

is computed over a FAST and a SLOW trailing window; the `slo_burn_rate`
alert goes active only when BOTH exceed the burn threshold. The fast
window makes a real incident page within seconds; the slow window
makes a short burst that never endangers the budget NOT page — the
flapping suppression single-threshold rules can't give. Resolution
carries hysteresis: once breaching, an objective stays active until
the fast burn cools below `threshold x resolve_factor`, so a burn
oscillating around the threshold pages once, not every other cycle.

Budgets are exported every cycle as `weed_slo_burn_rate{objective,
window}` and `weed_slo_budget_remaining{objective}`; the engine also
emits the SLO SCORECARD — availability, accepted p99.9, retry
amplification, MTTR, bytes-moved-per-rebuilt-byte, and a per-objective
verdict — the object `bench.py chaos --soak` consumes as the standing
regression gate (ROADMAP "production-day soak").

`WEED_SLO=0` disables the engine (the collector then runs exactly the
pre-weedscope rule set); window/threshold knobs: `WEED_SLO_FAST_S`,
`WEED_SLO_SLOW_S`, `WEED_SLO_BURN`. Both windows must fit the ring's
retention (ring_cap x scrape interval — 40 min at the defaults).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from seaweedfs_tpu.stats.metrics import SLO_BUDGET_REMAINING, SLO_BURN_RATE
from seaweedfs_tpu.telemetry.alerts import AlertRule
from seaweedfs_tpu.telemetry.ring import quantile_from_buckets

RULE_SLO_BURN = AlertRule(
    "slo_burn_rate", "critical", 0.0,
    "SLO error budget burning faster than the threshold over BOTH the "
    "fast and slow windows (multi-window multi-burn-rate: a burst that "
    "only burns the fast window never fires)",
)


def enabled() -> bool:
    return os.environ.get("WEED_SLO", "1") != "0"


@dataclass(frozen=True)
class SLOObjective:
    """One declarative objective.

    kind "availability": `target` is the good-request fraction; bad =
    5xx responses excluding 503/504, which are client-attributable by
    the health plane's doctrine (docs/HEALTH.md) — a tenant over its
    admission budget must not burn the cluster's SLO.

    kind "latency": `target` is the fraction of requests that must
    finish within `threshold_s`, measured from `family`'s buckets
    (optionally filtered to one `plane` — weed_span_seconds carries the
    plane label, weed_http_request_seconds is serve-only by nature)."""

    name: str
    kind: str  # availability | latency
    target: float  # good fraction, e.g. 0.999
    plane: str = "serve"
    daemon_kind: str = ""  # scrape-target kind filter; "" = all
    family: str = ""
    threshold_s: float = 0.5

    def describe(self) -> str:
        if self.kind == "availability":
            return f"{self.target:.4%} non-5xx"
        return (
            f"{self.target:.2%} of {self.plane} under "
            f"{self.threshold_s * 1000.0:.0f}ms"
        )


# The default objective set: cluster-wide serve availability and
# latency, volume-server availability (the data plane's own number,
# undiluted by gateways), and a tail-latency objective per background
# plane so repair/scrub/tier interference with serving has a budget of
# its own (PAPERS.md arXiv:1309.0186 — the interference is only
# manageable once it is measured against an explicit target).
DEFAULT_OBJECTIVES = (
    SLOObjective(
        "serve-availability", "availability", 0.999,
        family="weed_http_request_total",
    ),
    SLOObjective(
        "volume-availability", "availability", 0.999,
        daemon_kind="volume", family="weed_http_request_total",
    ),
    SLOObjective(
        "serve-latency", "latency", 0.99,
        family="weed_http_request_seconds", threshold_s=0.3,
    ),
    SLOObjective(
        "scrub-latency", "latency", 0.95, plane="scrub",
        family="weed_span_seconds", threshold_s=3.0,
    ),
    SLOObjective(
        "repair-latency", "latency", 0.95, plane="repair",
        family="weed_span_seconds", threshold_s=10.0,
    ),
    SLOObjective(
        "tier-latency", "latency", 0.95, plane="tier",
        family="weed_span_seconds", threshold_s=10.0,
    ),
)

_EPS = 1e-9


def _is_5xx_server_fault(labels: dict) -> bool:
    s = labels.get("status", "")
    return s.startswith("5") and s not in ("503", "504")


class SLOEngine:
    """Evaluates objectives against the collector's TargetStores and
    owns the burn-rate alert's hysteresis state. One instance per
    leader collector; stateless across restarts by design (budgets are
    windowed, not epoch-accounted — the windows ARE the state)."""

    def __init__(
        self,
        objectives: tuple[SLOObjective, ...] | list[SLOObjective] | None = None,
        fast_s: float | None = None,
        slow_s: float | None = None,
        burn_threshold: float | None = None,
        resolve_factor: float = 0.5,
    ):
        def _f(raw: str, default: float) -> float:
            try:
                return float(raw or default)
            except ValueError:
                return default

        self.objectives = tuple(objectives or DEFAULT_OBJECTIVES)
        # 5m/1h is the workbook's fast pair; soak/bench runs hand in
        # seconds-scale windows via telemetry_kwargs instead
        if fast_s is None:
            fast_s = _f(os.environ.get("WEED_SLO_FAST_S", ""), 300.0)
        self.fast_s = fast_s
        if slow_s is None:
            slow_s = _f(os.environ.get("WEED_SLO_SLOW_S", ""), 1800.0)
        self.slow_s = max(slow_s, self.fast_s)
        if burn_threshold is None:
            burn_threshold = _f(os.environ.get("WEED_SLO_BURN", ""), 1.0)
        self.burn_threshold = burn_threshold
        self.resolve_factor = max(0.0, min(1.0, resolve_factor))
        self._lock = threading.Lock()
        self._breaching: set[str] = set()
        self._rows: list[dict] = []
        self.last_eval_unix = 0.0

    # ------------------------------------------------------------------
    # measurement
    def _match(self, obj: SLOObjective, ts) -> bool:
        return not obj.daemon_kind or ts.kind == obj.daemon_kind

    def _bad_total(
        self, obj: SLOObjective, targets, window_s: float, now: float
    ) -> tuple[float, float]:
        """(bad, total) observation increases over the window, summed
        across matching targets."""
        bad = total = 0.0
        if obj.kind == "availability":
            family = obj.family or "weed_http_request_total"
            for ts in targets:
                if not self._match(obj, ts):
                    continue
                total += ts.increase_sum(family, window_s, now)
                bad += ts.increase_sum(
                    family, window_s, now, label_filter=_is_5xx_server_fault
                )
            return bad, total
        pooled = self._pooled_buckets(obj, targets, window_s, now)
        if not pooled:
            return 0.0, 0.0
        total = pooled.get(float("inf"), 0.0)
        # good = observations at-or-under the tightest bound >= the
        # threshold (conservative: a threshold between buckets judges
        # against the next bound up)
        finite = sorted(b for b in pooled if b != float("inf"))
        chosen = next(
            (b for b in finite if b >= obj.threshold_s - _EPS), float("inf")
        )
        good = pooled.get(chosen, total)
        return max(0.0, total - good), total

    def _pooled_buckets(
        self, obj: SLOObjective, targets, window_s: float, now: float
    ) -> dict[float, float]:
        plane = obj.plane

        def label_filter(labels: dict, _p=plane) -> bool:
            lp = labels.get("plane")
            return lp is None or lp == _p

        pooled: dict[float, float] = {}
        for ts in targets:
            if not self._match(obj, ts):
                continue
            for bound, inc in ts.bucket_increases(
                obj.family, window_s, now, label_filter=label_filter
            ).items():
                pooled[bound] = pooled.get(bound, 0.0) + inc
        return pooled

    @staticmethod
    def _burn(bad: float, total: float, target: float) -> float:
        if total <= _EPS:
            return 0.0
        return (bad / total) / max(_EPS, 1.0 - target)

    # ------------------------------------------------------------------
    # evaluation
    def evaluate(self, targets, now: float | None = None):
        """One cycle: compute both windows' burns per objective, drive
        the hysteresis state machine, export the gauges, and return
        AlertManager condition tuples for the collector to merge into
        its rule evaluation."""
        now = time.time() if now is None else now
        conds = []
        rows: list[dict] = []
        thr = self.burn_threshold
        for obj in self.objectives:
            bad_f, total_f = self._bad_total(obj, targets, self.fast_s, now)
            bad_s, total_s = self._bad_total(obj, targets, self.slow_s, now)
            burn_fast = self._burn(bad_f, total_f, obj.target)
            burn_slow = self._burn(bad_s, total_s, obj.target)
            budget = max(0.0, 1.0 - burn_slow)
            SLO_BURN_RATE.set(round(burn_fast, 4), obj.name, "fast")
            SLO_BURN_RATE.set(round(burn_slow, 4), obj.name, "slow")
            SLO_BUDGET_REMAINING.set(round(budget, 4), obj.name)
            with self._lock:
                if obj.name in self._breaching:
                    # hysteresis: stay active until the fast burn cools
                    # well below the threshold — no flap on resolve
                    active = burn_fast >= thr * self.resolve_factor
                else:
                    active = burn_fast > thr and burn_slow > thr
                if active:
                    self._breaching.add(obj.name)
                else:
                    self._breaching.discard(obj.name)
            verdict = (
                "burning" if active
                else ("at-risk" if max(burn_fast, burn_slow) > thr else "ok")
            )
            conds.append((
                RULE_SLO_BURN, obj.name, active, burn_fast,
                f"burn fast={burn_fast:.2f}x slow={burn_slow:.2f}x "
                f"(threshold {thr:.2f}x, objective {obj.describe()})",
            ))
            rows.append({
                "Objective": obj.name,
                "Kind": obj.kind,
                "Plane": obj.plane,
                "DaemonKind": obj.daemon_kind,
                "Target": obj.target,
                "ThresholdSeconds": obj.threshold_s
                if obj.kind == "latency" else None,
                "BurnFast": round(burn_fast, 4),
                "BurnSlow": round(burn_slow, 4),
                "BudgetRemaining": round(budget, 4),
                "BadFast": round(bad_f, 3),
                "TotalFast": round(total_f, 3),
                "BadSlow": round(bad_s, 3),
                "TotalSlow": round(total_s, 3),
                "Verdict": verdict,
            })
        with self._lock:
            self._rows = rows
            self.last_eval_unix = now
        return conds

    # ------------------------------------------------------------------
    # operator payloads
    def payload(self) -> dict:
        with self._lock:
            rows = [dict(r) for r in self._rows]
            breaching = sorted(self._breaching)
        return {
            "FastWindowSeconds": self.fast_s,
            "SlowWindowSeconds": self.slow_s,
            "BurnThreshold": self.burn_threshold,
            "LastEvalUnix": round(self.last_eval_unix, 3),
            "Breaching": breaching,
            "Objectives": rows,
        }

    def scorecard(self, targets, now: float | None = None) -> dict:
        """The soak gate's summary object (ROADMAP: availability,
        accepted p99.9, retry amplification, MTTR, bytes-moved-per-
        rebuilt-byte), measured over the slow window, plus the
        per-objective burn verdicts from the latest evaluation."""
        now = time.time() if now is None else now
        w = self.slow_s
        total = bad = retries = 0.0
        ttr_sum = ttr_count = 0.0
        rb_read = rb_written = 0.0
        pooled_http: dict[float, float] = {}
        for ts in targets:
            total += ts.increase_sum("weed_http_request_total", w, now)
            bad += ts.increase_sum(
                "weed_http_request_total", w, now,
                label_filter=_is_5xx_server_fault,
            )
            retries += ts.increase_sum("weed_retry_total", w, now)
            ttr_sum += ts.increase_sum(
                "weed_time_to_repair_seconds_sum", w, now
            )
            ttr_count += ts.increase_sum(
                "weed_time_to_repair_seconds_count", w, now
            )
            rb_read += ts.increase_sum(
                "weed_ec_repair_bytes_read_total", w, now
            )
            rb_written += ts.increase_sum(
                "weed_ec_repair_bytes_written_total", w, now
            )
            for bound, inc in ts.bucket_increases(
                "weed_http_request_seconds", w, now
            ).items():
                pooled_http[bound] = pooled_http.get(bound, 0.0) + inc
        p999 = quantile_from_buckets(pooled_http, 0.999)
        with self._lock:
            rows = [dict(r) for r in self._rows]
        return {
            "WindowSeconds": w,
            "Requests": round(total, 3),
            "AvailabilityPct": round(
                100.0 * (1.0 - (bad / total if total > _EPS else 0.0)), 4
            ),
            "AcceptedP999Ms": None if p999 is None else round(p999 * 1000.0, 3),
            "RetryAmplification": round(
                (total + retries) / total, 4
            ) if total > _EPS else 1.0,
            "MTTRSeconds": round(ttr_sum / ttr_count, 3)
            if ttr_count > _EPS else None,
            "BytesMovedPerRebuiltByte": round(rb_read / rb_written, 4)
            if rb_written > _EPS else None,
            "Objectives": rows,
        }
