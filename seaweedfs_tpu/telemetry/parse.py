"""Prometheus text exposition format 0.0.4 parser.

The collector scrapes /metrics from daemons that render through
stats/metrics.Registry, but the parser accepts the full text format
(escaped label values, exponent floats, +Inf/NaN) so a node running a
different exporter — or a future Go-reference sidecar — scrapes the
same way. Deliberately allocation-light: one pass per line, no regex.
"""

from __future__ import annotations

Sample = tuple[str, tuple[tuple[str, str], ...], float]


def _close_brace(line: str, start: int) -> int:
    """Index of the first UNQUOTED '}' at/after `start`, or -1.

    rfind('}') is wrong since weedscope: a bucket line may carry an
    exemplar suffix (`... {trace_id="..."} 0.09`) whose closing brace
    sits AFTER the value — rfind would swallow the sample value into
    the label body and drop the line. Quote-aware forward scan instead
    (a label VALUE may legally contain '}')."""
    i, n = start, len(line)
    in_quotes = False
    while i < n:
        c = line[i]
        if c == "\\" and in_quotes:
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
        elif c == "}" and not in_quotes:
            return i
        i += 1
    return -1


def _parse_labels(body: str) -> tuple[tuple[str, str], ...]:
    """`k="v",k2="v2"` → sorted ((k, v), ...) with \\" \\\\ \\n unescaped."""
    labels: list[tuple[str, str]] = []
    i, n = 0, len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            break
        name = body[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if i >= n or body[i] != '"':
            break  # malformed; keep what we have
        i += 1
        out: list[str] = []
        while i < n:
            c = body[i]
            if c == "\\" and i + 1 < n:
                nxt = body[i + 1]
                out.append("\n" if nxt == "n" else nxt)
                i += 2
                continue
            if c == '"':
                i += 1
                break
            out.append(c)
            i += 1
        labels.append((name, "".join(out)))
        while i < n and body[i] in ", ":
            i += 1
    labels.sort()
    return tuple(labels)


def parse_prometheus_text(text: str) -> list[Sample]:
    """Parse exposition text into (name, sorted label tuple, value)
    samples. Comment/HELP/TYPE lines and malformed lines are skipped —
    a scrape must degrade, not raise, on one bad line."""
    samples: list[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # name{labels} value [timestamp] [# {exemplar labels} ev]
        #   |   name value [timestamp]
        if "{" in line:
            brace = line.index("{")
            name = line[:brace]
            close = _close_brace(line, brace + 1)
            if close < brace:
                continue
            label_body = line[brace + 1 : close]
            rest = line[close + 1 :].strip()
            labels = _parse_labels(label_body)
        else:
            parts = line.split(None, 1)
            if len(parts) != 2:
                continue
            name, rest = parts
            labels = ()
        # an exemplar suffix (`# {...} v`) is not part of the sample
        rest = rest.partition("#")[0].strip()
        value_str = rest.split()[0] if rest else ""
        try:
            value = float(value_str)  # handles +Inf/-Inf/NaN spellings
        except ValueError:
            continue
        if name:
            samples.append((name, labels, value))
    return samples
