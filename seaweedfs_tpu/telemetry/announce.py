"""Gateway → master registration heartbeats.

Volume servers are discovered from their gRPC heartbeats, but the
filer/S3/WebDAV gateways have no channel to the master the collector
could observe — so they announce themselves over plain HTTP:
`GET /cluster/register?kind=<k>&addr=<host:port>` on an interval. The
master records (kind, addr, last_seen); the collector turns entries
into scrape targets. Registration is best-effort and rotates through
the master list on failure (any master accepts; followers proxy the
GET to the leader the same way /vol/vacuum does) — a dead master must
never take a gateway down with it.
"""

from __future__ import annotations

import threading
import urllib.parse
import urllib.request

from seaweedfs_tpu.util import wlog


def start_announce_loop(
    kind: str,
    addr: str,
    masters: list[str],
    interval: float = 10.0,
    stop_event: threading.Event | None = None,
) -> threading.Thread | None:
    """Announce `addr` as a `kind` gateway to the first reachable
    master every `interval` seconds until `stop_event` is set. Returns
    the loop thread (None when there are no masters to announce to)."""
    masters = [m for m in masters if m]
    if not masters:
        return None
    stop = stop_event or threading.Event()
    q = urllib.parse.urlencode({"kind": kind, "addr": addr})
    state = {"idx": 0, "warned": False}

    def announce_once() -> bool:
        for _ in range(len(masters)):
            m = masters[state["idx"] % len(masters)]
            try:
                with urllib.request.urlopen(
                    f"http://{m}/cluster/register?{q}", timeout=5
                ) as r:
                    r.read()
                state["warned"] = False
                return True
            except OSError as e:
                state["idx"] += 1
                last_err = e
        if not state["warned"]:  # log once per outage, not per tick
            state["warned"] = True
            wlog.warning(
                "telemetry: %s %s cannot register with any master "
                "(last: %s); will keep retrying",
                kind, addr, last_err,
            )
        return False

    def loop():
        announce_once()
        while not stop.wait(interval):
            announce_once()

    t = threading.Thread(
        target=loop, daemon=True, name=f"announce-{kind}"
    )
    t.stop_event = stop
    t.start()
    return t
