"""Cluster telemetry plane (docs/TELEMETRY.md).

PR 5 gave every request a trace; this plane makes the CLUSTER visible:

  parse.py     Prometheus text-format 0.0.4 parser (the wire format
               every daemon's /metrics already speaks)
  ring.py      fixed-retention in-process ring TSDB: per-series sample
               rings with counter-reset-aware rate/increase and
               histogram-bucket quantiles
  collector.py leader-only master scraper: volume servers discovered
               from heartbeats, gateways via /cluster/register, with
               per-target staleness + last-error tracking
  alerts.py    SLO alert rules with firing→resolved transitions,
               re-exported as weed_alert_firing gauges
  profiler.py  continuous sampling profiler on every daemon
               (sys._current_frames() → folded stacks, /debug/profile)
  announce.py  gateway → master registration heartbeats
  weedload.py  multi-process closed-loop load harness with
               coordinated-omission-safe log-bucketed histograms

The aggregation-only design follows the reference's shape
(weed/stats/metrics.go push loop + weed/shell cluster commands) and the
Facebook warehouse study (arXiv:1309.0186): fleet-level interference —
repair traffic stealing serving bandwidth, one slow node dragging the
cluster p99.9 — is only visible in aggregated telemetry, never in any
single daemon's counters.
"""

from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule
from seaweedfs_tpu.telemetry.collector import ClusterCollector
from seaweedfs_tpu.telemetry.parse import parse_prometheus_text
from seaweedfs_tpu.telemetry.ring import SeriesRing, TargetStore

__all__ = [
    "AlertManager",
    "AlertRule",
    "ClusterCollector",
    "SeriesRing",
    "TargetStore",
    "parse_prometheus_text",
]
