"""Incident capsules (weedscope, docs/TELEMETRY.md).

When an alert transitions to firing — or an operator runs
`capsule.capture` — the node snapshots its volatile evidence into a
durably-published capsule directory: the blackbox flight-recorder ring
(trace/blackbox.py), the completed-span ring (/debug/traces), the
sampling profiler's folded stacks, the current /metrics exposition,
and — on the leader — the relevant TSDB window plus the alert/SLO/
health verdicts. Minutes later, after rings have wrapped and gauges
have moved on, the capsule is still exactly what the node knew at the
moment the objective burned.

Publication rides util/durable.publish (fsync bytes → rename → fsync
dir) file by file, with MANIFEST.json published LAST: a capsule is
valid if and only if its manifest exists, so a crash mid-capture
leaves a garbage-collectable partial, never a plausible-looking lie.

Process-global by design: providers register once per daemon process;
the per-node HTTP surface (`/capsule/capture`, `/capsule/list`,
`/capsule/get`) is served by the mini-loop funnel on EVERY daemon, and
the leader-side `capsule.collect` shell verb merges per-node capsules
by trace id into one cross-node incident view.

Knobs: `WEED_CAPSULE_DIR` (default <tmp>/weed-capsules),
`WEED_CAPSULE_KEEP` retained capsules (default 8),
`WEED_CAPSULE_COOLDOWN_S` per-(alert,target) auto-capture damping
(default 60). `WEED_SCOPE=0` disables auto-capture with the rest of
the weedscope plane; manual capture keeps working (an operator asking
for evidence should always get it).
"""

from __future__ import annotations

import itertools
import json
import os
import re
import shutil
import tempfile
import threading
import time
import urllib.parse
import urllib.request

from seaweedfs_tpu.stats.metrics import CAPSULE_CAPTURES
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.durable import fsync_dir, publish

_KEEP = max(1, int(os.environ.get("WEED_CAPSULE_KEEP", "8") or 8))
_COOLDOWN_S = float(os.environ.get("WEED_CAPSULE_COOLDOWN_S", "60") or 60)

_lock = threading.Lock()
_dir_override: str | None = None
_seq = itertools.count()
_last_capture: dict[str, float] = {}  # cooldown key -> unix time

# name -> (fn, kind); kind "json" (fn returns a JSON-able object) or
# "text" (fn returns str). Ordered: the manifest lists files in
# registration order.
_providers: dict[str, tuple] = {}

_SLUG_RE = re.compile(r"[^a-zA-Z0-9_.-]+")
_ID_RE = re.compile(r"^[0-9]{10,}-[0-9]+-[a-zA-Z0-9_.-]+$")


def capsule_dir() -> str:
    with _lock:
        if _dir_override:
            return _dir_override
    return os.environ.get("WEED_CAPSULE_DIR", "") or os.path.join(
        tempfile.gettempdir(), "weed-capsules"
    )


def set_dir(path: str) -> None:
    """Daemon/test override for the capsule directory (a volume server
    colocating capsules with its data disks, a bench isolating runs)."""
    global _dir_override
    with _lock:
        _dir_override = path or None


def add_provider(name: str, fn, kind: str = "json") -> None:
    """Register a capsule section. `fn()` is called at capture time and
    must be exception-safe-ish — a raising provider is recorded in the
    manifest as failed, never aborts the capsule (partial evidence
    beats none)."""
    with _lock:
        _providers[name] = (fn, kind)


def _default_providers() -> None:
    """The sections every daemon gets. Imports are deferred to capture
    time so merely importing this module costs nothing."""

    def blackbox():
        from seaweedfs_tpu.trace import blackbox as bb

        return bb.snapshot(512)

    def traces():
        from seaweedfs_tpu.trace import tracer

        return tracer.debug_payload(256)

    def profile():
        from seaweedfs_tpu.telemetry import profiler

        # seconds=0: the instant since-start aggregate — capture must
        # not park the alert path for a sampling window
        return profiler.render_folded(profiler.capture(0.0))

    def metrics():
        from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY

        return DEFAULT_REGISTRY.render_text()

    add_provider("blackbox", blackbox, "json")
    add_provider("traces", traces, "json")
    add_provider("profile", profile, "text")
    add_provider("metrics", metrics, "text")


_default_providers()


def _publish_bytes(cap_dir: str, name: str, data: bytes) -> None:
    tmp = os.path.join(cap_dir, f".{name}.tmp")
    with open(tmp, "wb") as f:
        f.write(data)
    publish(tmp, os.path.join(cap_dir, name))


def capture(
    reason: str, trigger: str = "manual", node: str = "", root: str | None = None
) -> dict:
    """Snapshot every provider into a new capsule directory; returns
    the manifest (id, node, files, per-provider status)."""
    now = time.time()
    slug = _SLUG_RE.sub("-", reason or "manual")[:80].strip("-.") or "manual"
    cap_id = f"{int(now * 1000):013d}-{next(_seq)}-{slug}"
    base = root or capsule_dir()
    cap_dir = os.path.join(base, cap_id)
    os.makedirs(cap_dir, exist_ok=True)
    with _lock:
        providers = dict(_providers)
    files: list[dict] = []
    for name, (fn, kind) in providers.items():
        fname = name + (".json" if kind == "json" else ".txt")
        try:
            payload = fn()
            data = (
                json.dumps(payload).encode()
                if kind == "json"
                else str(payload).encode()
            )
            _publish_bytes(cap_dir, fname, data)
            files.append({"Name": fname, "Bytes": len(data), "Ok": True})
        except Exception as e:  # noqa: BLE001 — partial evidence > none
            files.append({"Name": fname, "Ok": False, "Error": str(e)[:300]})
    manifest = {
        "Id": cap_id,
        "Reason": reason,
        "Trigger": trigger,
        "Node": node,
        "CapturedAtUnix": round(now, 3),
        "Files": files,
    }
    # the manifest goes LAST: its presence is the capsule's validity
    _publish_bytes(cap_dir, "MANIFEST.json", json.dumps(manifest).encode())
    fsync_dir(base)
    CAPSULE_CAPTURES.labels(trigger).inc()
    wlog.info("capsule captured %s (%s) -> %s", cap_id, reason, cap_dir)
    _prune(base)
    return manifest


def _prune(base: str) -> None:
    """Bounded retention: keep the newest WEED_CAPSULE_KEEP valid
    capsules; manifest-less partials older than an hour are crash
    leftovers and go too."""
    try:
        entries = sorted(os.listdir(base))
    except OSError:
        return
    valid = [
        e for e in entries
        if _ID_RE.match(e)
        and os.path.exists(os.path.join(base, e, "MANIFEST.json"))
    ]
    doomed = valid[:-_KEEP] if len(valid) > _KEEP else []
    cutoff = time.time() - 3600.0
    for e in entries:
        if not _ID_RE.match(e) or e in valid:
            continue
        try:
            if os.path.getmtime(os.path.join(base, e)) < cutoff:
                doomed.append(e)
        except OSError:
            continue
    for e in doomed:
        shutil.rmtree(os.path.join(base, e), ignore_errors=True)


def list_capsules(root: str | None = None) -> list[dict]:
    """Manifests of every valid capsule, oldest first."""
    base = root or capsule_dir()
    out: list[dict] = []
    try:
        entries = sorted(os.listdir(base))
    except OSError:
        return out
    for e in entries:
        if not _ID_RE.match(e):
            continue
        try:
            with open(os.path.join(base, e, "MANIFEST.json"), "rb") as f:
                out.append(json.loads(f.read()))
        except (OSError, ValueError):
            continue
    return out


def read_file(cap_id: str, name: str, root: str | None = None) -> bytes | None:
    """One capsule file's bytes, with the id/name validated against
    the capsule naming scheme (this backs an HTTP endpoint — no path
    traversal)."""
    if not _ID_RE.match(cap_id) or "/" in name or name.startswith("."):
        return None
    try:
        with open(os.path.join(root or capsule_dir(), cap_id, name), "rb") as f:
            return f.read()
    except OSError:
        return None


# ---------------------------------------------------------------------------
# alert-triggered capture


def should_autocapture(key: str, now: float | None = None) -> bool:
    """Per-(alert,target) cooldown gate so one flapping rule cannot
    churn the capsule directory through its retention bound."""
    now = time.time() if now is None else now
    with _lock:
        if now - _last_capture.get(key, 0.0) < _COOLDOWN_S:
            return False
        _last_capture[key] = now
        return True


class CaptureCoordinator:
    """The AlertManager on_fire hook: captures a local capsule and asks
    every implicated peer to capture one too (their `/capsule/capture`
    endpoint), off-thread — the alert evaluation cycle must never block
    on capsule I/O.

    `peers_fn(alert_row) -> [host:port, ...]` names the implicated
    nodes: the master passes the alert's target when it looks like a
    node, or the up scrape targets for cluster-scoped alerts (an SLO
    objective burning implicates everyone serving it)."""

    def __init__(self, node: str = "", peers_fn=None, enabled_fn=None):
        self.node = node
        self.peers_fn = peers_fn
        self.enabled_fn = enabled_fn

    def __call__(self, alert_row: dict) -> None:
        if self.enabled_fn is not None and not self.enabled_fn():
            return
        key = f"{alert_row.get('Alert')}@{alert_row.get('Target')}"
        if not should_autocapture(key):
            return
        threading.Thread(
            target=self._run, args=(alert_row, key), daemon=True,
            name="capsule-capture",
        ).start()

    def _run(self, alert_row: dict, key: str) -> None:
        reason = f"alert-{key}"
        try:
            capture(reason, trigger="alert", node=self.node)
        except Exception as e:  # noqa: BLE001 — capture must not throw
            wlog.warning("capsule: local capture failed: %r", e)
        for url in self._peers(alert_row):
            try:
                q = urllib.parse.urlencode(
                    {"reason": reason, "trigger": "alert"}
                )
                with urllib.request.urlopen(
                    f"http://{url}/capsule/capture?{q}", timeout=10.0
                ) as r:
                    r.read()
            except OSError as e:
                wlog.warning(
                    "capsule: remote capture on %s failed: %r", url, e
                )

    def _peers(self, alert_row: dict) -> list[str]:
        if self.peers_fn is None:
            return []
        try:
            peers = list(self.peers_fn(alert_row) or ())
        except Exception:  # noqa: BLE001
            return []
        return [u for u in peers if u and u != self.node]
