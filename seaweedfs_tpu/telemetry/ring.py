"""Fixed-retention in-process ring TSDB.

One `SeriesRing` per scraped series: a preallocated (time, value) ring
whose capacity IS the retention policy — no compaction, no disk, no
unbounded growth no matter how long the master runs. A `TargetStore`
holds every series scraped from one node plus the scrape-health
bookkeeping (last success, last error, staleness) the alert rules and
/cluster/health read.

Counters are handled reset-aware: `increase()` sums positive adjacent
deltas so a daemon restart (counter back to 0) contributes nothing
instead of a huge negative spike — the classic naive last-minus-first
bug every homegrown scraper ships once.
"""

from __future__ import annotations

import threading
import time

from seaweedfs_tpu.stats.quantile import histogram_quantile

LabelTuple = tuple[tuple[str, str], ...]
SeriesKey = tuple[str, LabelTuple]


def quantile_from_buckets(
    by_le: dict[float, float], q: float
) -> float | None:
    """histogram_quantile over a {le bound: cumulative count} map (the
    shape bucket_increases returns — possibly pooled across several
    TargetStores by the SLO engine). None when the map saw nothing."""
    if not by_le:
        return None
    bounds = sorted(by_le)
    cum = [by_le[b] for b in bounds]
    # cumulative → per-bucket counts
    counts = [cum[0]] + [
        max(0.0, cum[i] - cum[i - 1]) for i in range(1, len(cum))
    ]
    if sum(counts) <= 0:
        return None
    finite_bounds = [b for b in bounds if b != float("inf")]
    if len(finite_bounds) < len(bounds):
        # fold the +Inf bucket into the overflow slot
        counts = counts[: len(finite_bounds)] + [counts[-1]]
    return histogram_quantile(finite_bounds, counts, q)


class SeriesRing:
    """Preallocated (t, v) ring; append overwrites the oldest sample."""

    __slots__ = ("_t", "_v", "_next", "count", "cap")

    def __init__(self, cap: int = 240):
        self.cap = cap
        self._t = [0.0] * cap
        self._v = [0.0] * cap
        self._next = 0
        self.count = 0

    def append(self, t: float, v: float) -> None:
        i = self._next
        self._t[i] = t
        self._v[i] = v
        self._next = (i + 1) % self.cap
        if self.count < self.cap:
            self.count += 1

    def items(self) -> list[tuple[float, float]]:
        """Samples oldest → newest."""
        if self.count < self.cap:
            return [(self._t[i], self._v[i]) for i in range(self.count)]
        start = self._next
        return [
            (self._t[(start + i) % self.cap], self._v[(start + i) % self.cap])
            for i in range(self.cap)
        ]

    def last(self) -> tuple[float, float] | None:
        if self.count == 0:
            return None
        i = (self._next - 1) % self.cap
        return self._t[i], self._v[i]

    def window(self, window_s: float, now: float | None = None
               ) -> list[tuple[float, float]]:
        """Samples within the trailing window, oldest → newest."""
        now = time.time() if now is None else now
        lo = now - window_s
        return [(t, v) for t, v in self.items() if t >= lo]

    def increase(self, window_s: float, now: float | None = None) -> float:
        """Counter increase over the window: sum of positive adjacent
        deltas (reset-aware). 0.0 with fewer than two samples."""
        pts = self.window(window_s, now)
        total = 0.0
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if v1 > v0:
                total += v1 - v0
        return total

    def rate(self, window_s: float, now: float | None = None) -> float:
        """Counter per-second rate over the window (increase / span)."""
        pts = self.window(window_s, now)
        if len(pts) < 2:
            return 0.0
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return 0.0
        return self.increase(window_s, now) / span


class TargetStore:
    """Every series scraped from one target, plus scrape health.

    `record_scrape` ingests one parsed scrape atomically under the
    store lock; readers (`rate_sum`, `quantile`, health snapshots) take
    the same lock, so a half-ingested scrape is never visible — the
    same snapshot-consistency contract Registry.render_text keeps on
    the producing side."""

    def __init__(self, url: str, kind: str, ring_cap: int = 240):
        self.url = url
        self.kind = kind
        self.ring_cap = ring_cap
        self.series: dict[SeriesKey, SeriesRing] = {}
        self.last_success = 0.0
        self.last_attempt = 0.0
        self.last_error = ""
        self.scrapes = 0
        self.first_seen = time.time()
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # ingest
    def record_scrape(self, samples, t: float | None = None) -> None:
        t = time.time() if t is None else t
        with self._lock:
            for name, labels, value in samples:
                key = (name, labels)
                ring = self.series.get(key)
                if ring is None:
                    ring = self.series[key] = SeriesRing(self.ring_cap)
                ring.append(t, value)
            self.last_success = self.last_attempt = t
            self.last_error = ""
            self.scrapes += 1

    def record_failure(self, err: str, t: float | None = None) -> None:
        with self._lock:
            self.last_attempt = time.time() if t is None else t
            self.last_error = err[:300]

    # ------------------------------------------------------------------
    # reads
    def staleness(self, now: float | None = None) -> float:
        """Seconds since the last successful scrape; since first sight
        when none ever succeeded (so a never-up target goes stale too)."""
        now = time.time() if now is None else now
        return now - (self.last_success or self.first_seen)

    def series_count(self) -> int:
        with self._lock:
            return len(self.series)

    def last_value(self, name: str, **labels: str) -> float | None:
        """Newest sample of the series matching name + label SUBSET."""
        want = set(labels.items())
        with self._lock:
            newest: tuple[float, float] | None = None
            for (n, lt), ring in self.series.items():
                if n != name or not want.issubset(lt):
                    continue
                last = ring.last()
                if last is not None and (newest is None or last[0] > newest[0]):
                    newest = last
        return newest[1] if newest else None

    def rate_sum(
        self,
        name: str,
        window_s: float,
        now: float | None = None,
        label_filter=None,
    ) -> float:
        """Per-second rate of a counter family over the window, summed
        across every series of that name (optionally filtered by
        `label_filter(labels_dict) -> bool`)."""
        total = 0.0
        with self._lock:
            for (n, lt), ring in self.series.items():
                if n != name:
                    continue
                if label_filter is not None and not label_filter(dict(lt)):
                    continue
                total += ring.rate(window_s, now)
        return total

    def increase_sum(
        self,
        name: str,
        window_s: float,
        now: float | None = None,
        label_filter=None,
    ) -> float:
        total = 0.0
        with self._lock:
            for (n, lt), ring in self.series.items():
                if n != name:
                    continue
                if label_filter is not None and not label_filter(dict(lt)):
                    continue
                total += ring.increase(window_s, now)
        return total

    def bucket_increases(
        self,
        family: str,
        window_s: float,
        now: float | None = None,
        label_filter=None,
    ) -> dict[float, float]:
        """Windowed increases of a histogram family's `<family>_bucket`
        series, keyed by `le` bound (cumulative, Prometheus-style) and
        aggregated across all non-`le` label splits (optionally
        filtered). The shared primitive under quantile() and the SLO
        engine's latency objectives (telemetry/slo.py): the entry at a
        bound is how many observations landed at-or-under it in the
        window, the `+Inf` entry is the window's total count."""
        bucket_name = family + "_bucket"
        by_le: dict[float, float] = {}
        with self._lock:
            for (n, lt), ring in self.series.items():
                if n != bucket_name:
                    continue
                labels = dict(lt)
                le = labels.pop("le", None)
                if le is None:
                    continue
                if label_filter is not None and not label_filter(labels):
                    continue
                bound = float("inf") if le in ("+Inf", "inf") else float(le)
                by_le[bound] = by_le.get(bound, 0.0) + ring.increase(
                    window_s, now
                )
        return by_le

    def quantile(
        self,
        family: str,
        q: float,
        window_s: float,
        now: float | None = None,
        label_filter=None,
    ) -> float | None:
        """Quantile estimate from a Prometheus histogram family's
        `<family>_bucket` series over the trailing window.

        Buckets arrive CUMULATIVE per scrape; the windowed increase per
        `le` is itself cumulative across les, so adjacent-le differences
        yield the per-bucket counts histogram_quantile wants. Returns
        None when the window saw no observations."""
        return quantile_from_buckets(
            self.bucket_increases(family, window_s, now, label_filter), q
        )

    def dump_window(
        self,
        prefixes: tuple[str, ...],
        window_s: float,
        now: float | None = None,
    ) -> dict[str, list[list[float]]]:
        """Raw [t, v] samples within the trailing window for every
        series whose family name starts with one of `prefixes` —
        the incident capsule's TSDB section (telemetry/capsule.py).
        Keyed by the Prometheus-rendered series identity so the dump
        round-trips through any promtext tooling."""
        out: dict[str, list[list[float]]] = {}
        with self._lock:
            for (n, lt), ring in self.series.items():
                if not n.startswith(prefixes):
                    continue
                pts = ring.window(window_s, now)
                if not pts:
                    continue
                if lt:
                    rendered = ",".join(f'{k}="{v}"' for k, v in lt)
                    key = f"{n}{{{rendered}}}"
                else:
                    key = n
                out[key] = [[round(t, 3), v] for t, v in pts]
        return out

    def health_row(
        self, now: float | None = None, stale_after: float | None = None
    ) -> dict:
        """Operator row. `Up` uses the SAME staleness grace as the
        weed_scrape_up gauge and the alert rule (one transient failed
        scrape must not read DOWN while the alert page stays green) —
        callers pass the collector's stale_after; None falls back to
        the strict last-scrape-succeeded view."""
        now = time.time() if now is None else now
        with self._lock:
            if stale_after is None:
                up = bool(
                    self.last_success
                    and self.last_success >= self.last_attempt
                )
            else:
                up = bool(
                    self.last_success
                    and now - self.last_success < stale_after
                )
            return {
                "Kind": self.kind,
                "Up": up,
                "LastSuccessUnix": round(self.last_success, 3),
                "StalenessSeconds": round(
                    now - (self.last_success or self.first_seen), 3
                ),
                "LastError": self.last_error,
                "Scrapes": self.scrapes,
                "Series": len(self.series),
            }
