"""Leader-only master collector: scrape every node, ring it, alert.

Discovery is two-source, mirroring how the cluster already knows
itself: volume servers come off the heartbeat topology (the master
already holds them — no second membership protocol), gateways
(filer/S3/WebDAV) announce themselves over `/cluster/register`
(telemetry/announce.py) because nothing else in the control plane
knows they exist. Targets are STICKY: a node that drops out of the
topology (killed, frozen, partitioned) stays a scrape target until
`forget_after` so its staleness alert can fire — forgetting a dead
node instantly would resolve exactly the alert that matters most.

Every cycle: scrape all targets (bounded worker fan-out, per-target
timeout), ingest into the per-target ring TSDB, update the
staleness/up gauges, then evaluate the SLO rule set through the
AlertManager. Non-leaders idle — followers hold no topology, so their
aggregates would be empty lies.
"""

from __future__ import annotations

import threading
import time
import urllib.request

from seaweedfs_tpu.stats.metrics import SCRAPE_STALENESS, SCRAPE_UP
from seaweedfs_tpu.telemetry import slo as slo_mod
from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule
from seaweedfs_tpu.telemetry.parse import parse_prometheus_text
from seaweedfs_tpu.telemetry.ring import TargetStore
from seaweedfs_tpu.util import wlog

# The fixed SLO rule set (docs/TELEMETRY.md). for_s of one-ish scrape
# cycle on the flappable rules; staleness carries its own grace via the
# stale_factor threshold so for_s stays 0 (a target that missed 3
# scrapes is already long past "one slow cycle").
RULE_SCRAPE_STALE = AlertRule(
    "scrape_staleness", "critical", 0.0,
    "target unreachable: no successful /metrics scrape within the "
    "staleness bound (node down, frozen, or partitioned)",
)
RULE_ERROR_RATE = AlertRule(
    "error_rate", "critical", 0.0,
    "5xx fraction of served requests above threshold over the window",
)
RULE_SPAN_P99 = AlertRule(
    "span_p99", "warning", 0.0,
    "p99 span duration above threshold over the window",
)
RULE_SCRUB_CORRUPT = AlertRule(
    "scrub_corruptions", "critical", 0.0,
    "scrubber found new corruption on this node within the window",
)
RULE_REPAIR_DEPTH = AlertRule(
    "repair_queue_depth", "warning", 0.0,
    "master repair scheduler tracking more damage than the bound",
)
RULE_ADMISSION = AlertRule(
    "admission_reject_rate", "warning", 0.0,
    "per-client admission control shedding requests (503 + Retry-After) "
    "above the sustained-rate bound — a tenant is over budget or the "
    "node is saturated (docs/QOS.md)",
)
RULE_REPL_LAG = AlertRule(
    "replication_lag", "warning", 0.0,
    "cross-cluster replication consumer lag (uncommitted filer events "
    "in the notification queue) above the bound — the remote cluster "
    "is falling behind the local one (docs/TIERING.md)",
)


class ClusterCollector:
    def __init__(
        self,
        master,
        interval: float = 10.0,
        scrape_timeout: float = 5.0,
        ring_cap: int = 240,
        window_s: float = 120.0,
        stale_factor: float = 3.0,
        forget_after: float = 3600.0,
        error_rate_threshold: float = 0.05,
        span_p99_threshold_s: float = 2.0,
        repair_depth_threshold: int = 8,
        admission_reject_threshold: float = 1.0,
        repl_lag_threshold: float = 1000.0,
        slo_objectives=None,
        slo_fast_s: float | None = None,
        slo_slow_s: float | None = None,
        slo_burn_threshold: float | None = None,
    ):
        self.master = master
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        self.ring_cap = ring_cap
        # rate/quantile window; floored to a few scrape cycles so the
        # increase() math always has >= 2 samples at steady state
        self.window_s = max(window_s, 3.0 * interval)
        self.stale_after = max(stale_factor * interval, interval + 1.0)
        # dead-node TTL (the NodeHealth 1h prune, mirrored): the floor
        # guarantees the staleness alert gets its full firing window
        # before the target — and with it the alert's rule×target pair —
        # is forgotten and auto-resolved
        self.forget_after = max(forget_after, self.stale_after + 2.0 * interval)
        self.error_rate_threshold = error_rate_threshold
        self.span_p99_threshold_s = span_p99_threshold_s
        self.repair_depth_threshold = repair_depth_threshold
        self.admission_reject_threshold = admission_reject_threshold
        self.repl_lag_threshold = repl_lag_threshold
        self.alerts = AlertManager()
        self.slo = (
            slo_mod.SLOEngine(
                objectives=slo_objectives,
                fast_s=slo_fast_s,
                slow_s=slo_slow_s,
                burn_threshold=slo_burn_threshold,
            )
            if slo_mod.enabled()
            else None
        )
        self.targets: dict[str, TargetStore] = {}
        self._targets_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.last_cycle_unix = 0.0

    # ------------------------------------------------------------------
    # lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-collector"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if not self.master.is_leader:
                continue
            try:
                self.collect_once()
            except Exception as e:  # noqa: BLE001 — the plane must survive
                wlog.error("telemetry: collect cycle failed: %r", e)

    # ------------------------------------------------------------------
    # discovery
    def _discover(self) -> None:
        now = time.time()
        seen: dict[str, str] = {f"{self.master.host}:{self.master.port}": "master"}
        for dn in self.master.topology.data_nodes():
            seen[dn.url] = "volume"
        for addr, row in self.master.gateway_registrations().items():
            seen[addr] = row["kind"]
        with self._targets_lock:
            for url, kind in seen.items():
                ts = self.targets.get(url)
                if ts is None:
                    self.targets[url] = TargetStore(url, kind, self.ring_cap)
                elif ts.kind != kind:
                    ts.kind = kind
            # sticky forget: only targets BOTH absent from discovery
            # and stale past forget_after are dropped (their alerts
            # resolve via the evaluate() absent-pair rule)
            for url in [u for u in self.targets if u not in seen]:
                if self.targets[url].staleness(now) > self.forget_after:
                    del self.targets[url]
                    # remove, don't zero: a forgotten node must vanish
                    # from /metrics, not haunt it as a 0-valued row
                    SCRAPE_STALENESS.remove(url)
                    SCRAPE_UP.remove(url)
                    wlog.info(
                        "telemetry: forgot dead target %s after %.0fs",
                        url, self.forget_after,
                    )

    # ------------------------------------------------------------------
    # scrape
    def _scrape_one(self, ts: TargetStore) -> None:
        try:
            with urllib.request.urlopen(
                f"http://{ts.url}/metrics", timeout=self.scrape_timeout
            ) as r:
                text = r.read().decode("utf-8", "replace")
            ts.record_scrape(parse_prometheus_text(text))
        except (OSError, ValueError) as e:
            ts.record_failure(str(e))

    def collect_once(self) -> None:
        """One full cycle: discover → scrape (bounded fan-out) →
        gauges → alert evaluation. Also the test/bench seam: callers
        drive cycles synchronously without the background thread."""
        self._discover()
        with self._targets_lock:
            targets = list(self.targets.values())
        # bounded fan-out: one slow target must not serialize the cycle
        # behind its timeout, but concurrency stays capped at 8 however
        # many nodes register — chunked waves, not a thread per node.
        # A scrape stuck past its deadline (DNS stall is outside
        # urlopen's timeout) delays only its wave; the threads are
        # daemonic and urlopen's socket timeout bounds the common case.
        for i in range(0, len(targets), 8):
            wave = [
                threading.Thread(
                    target=self._scrape_one, args=(ts,), daemon=True
                )
                for ts in targets[i : i + 8]
            ]
            for t in wave:
                t.start()
            for t in wave:
                t.join(self.scrape_timeout + 2.0)
        now = time.time()
        for ts in targets:
            SCRAPE_STALENESS.set(round(ts.staleness(now), 3), ts.url)
            SCRAPE_UP.set(
                1.0 if (ts.last_success and ts.staleness(now) < self.stale_after)
                else 0.0,
                ts.url,
            )
        self._evaluate(targets, now)
        self.cycles += 1
        self.last_cycle_unix = now

    # ------------------------------------------------------------------
    # alert rules
    def _evaluate(self, targets: list[TargetStore], now: float) -> None:
        conds: list[tuple[AlertRule, str, bool, float, str]] = []
        w = self.window_s
        for ts in targets:
            stale = ts.staleness(now)
            conds.append((
                RULE_SCRAPE_STALE, ts.url, stale > self.stale_after, stale,
                f"last successful scrape {stale:.1f}s ago"
                + (f" ({ts.last_error})" if ts.last_error else ""),
            ))
            if not ts.last_success:
                continue  # no samples: only staleness can judge it
            total = ts.rate_sum("weed_http_request_total", w, now)
            errs = ts.rate_sum(
                "weed_http_request_total", w, now,
                label_filter=lambda l: l.get("status", "").startswith("5"),
            )
            frac = errs / total if total > 0.01 else 0.0
            conds.append((
                RULE_ERROR_RATE, ts.url,
                frac > self.error_rate_threshold, frac,
                f"{errs:.2f}/s of {total:.2f}/s requests are 5xx",
            ))
            p99 = ts.quantile("weed_span_seconds", 0.99, w, now)
            conds.append((
                RULE_SPAN_P99, ts.url,
                p99 is not None and p99 > self.span_p99_threshold_s,
                p99 or 0.0,
                f"span p99 {0.0 if p99 is None else p99 * 1000.0:.1f}ms "
                f"over {w:.0f}s",
            ))
            corrupt = ts.increase_sum(
                "weed_scrub_corruptions_found_total", w, now
            )
            conds.append((
                RULE_SCRUB_CORRUPT, ts.url, corrupt > 0, corrupt,
                f"{corrupt:.0f} new corruption(s) in {w:.0f}s",
            ))
            # QoS plane: sustained shedding means a tenant is over
            # budget (or the node is saturated) — surface it before the
            # tenant's own dashboards do
            shed = ts.rate_sum("weed_admission_rejected_total", w, now)
            conds.append((
                RULE_ADMISSION, ts.url,
                shed > self.admission_reject_threshold, shed,
                f"{shed:.2f}/s requests shed by admission control "
                f"over {w:.0f}s",
            ))
            # replication plane: the producer (filer) exposes the
            # consumer group's queue depth as a gauge — a consumer
            # that stalled (or was killed with WEED_REPL=0 and
            # forgotten) shows up as monotonically growing lag
            lag = ts.last_value("weed_replication_lag_events")
            conds.append((
                RULE_REPL_LAG, ts.url,
                lag is not None and lag > self.repl_lag_threshold,
                lag or 0.0,
                f"{0 if lag is None else lag:.0f} filer event(s) behind "
                f"(bound {self.repl_lag_threshold:.0f})",
            ))
        # master-local: the repair scheduler's tracked-damage depth
        depth = 0
        if getattr(self.master, "repair", None) is not None:
            try:
                depth = len(self.master.repair.queue_snapshot().get("Tasks", []))
            except Exception:  # noqa: BLE001 — telemetry must not throw
                depth = 0
        conds.append((
            RULE_REPAIR_DEPTH, f"{self.master.host}:{self.master.port}",
            depth > self.repair_depth_threshold, float(depth),
            f"{depth} damage task(s) tracked "
            f"(bound {self.repair_depth_threshold})",
        ))
        if self.slo is not None:
            conds.extend(self.slo.evaluate(targets, now))
        self.alerts.evaluate(conds, now)

    # ------------------------------------------------------------------
    # operator payloads
    def health_payload(self) -> dict:
        from seaweedfs_tpu.stats.metrics import push_status

        now = time.time()
        with self._targets_lock:
            rows = {
                url: ts.health_row(now, stale_after=self.stale_after)
                for url, ts in sorted(self.targets.items())
            }
        alerts = self.alerts.payload()
        return {
            "IsLeader": self.master.is_leader,
            "IntervalSeconds": self.interval,
            "WindowSeconds": self.window_s,
            "StaleAfterSeconds": round(self.stale_after, 3),
            "Cycles": self.cycles,
            "LastCycleUnix": round(self.last_cycle_unix, 3),
            "Targets": rows,
            "FiringAlerts": len(alerts["Firing"]),
            "PendingAlerts": len(alerts["Pending"]),
            "Push": push_status(),
        }

    def slo_payload(self) -> dict:
        """/cluster/slo body: engine config + latest per-objective burn
        rows + the soak-gate scorecard over the slow window."""
        if self.slo is None:
            return {"Enabled": False}
        with self._targets_lock:
            targets = list(self.targets.values())
        body = self.slo.payload()
        body["Enabled"] = True
        body["Scorecard"] = self.slo.scorecard(targets)
        return body

    # series families worth freezing into an incident capsule — the
    # request/span signals every objective reads, plus the alert/SLO
    # state itself; everything else stays out so a capsule of a
    # many-node cluster stays megabytes, not the whole TSDB
    _CAPSULE_FAMILIES = (
        "weed_http_request",
        "weed_span_seconds",
        "weed_scrape_",
        "weed_slo_",
        "weed_alert_firing",
        "weed_retry_total",
        "weed_time_to_repair_seconds",
        "weed_admission_rejected_total",
        "weed_scrub_corruptions_found_total",
    )

    def window_payload(self, window_s: float | None = None) -> dict:
        """The capsule's TSDB section: the relevant families' raw
        samples over the SLO slow window (or `window_s`), per target."""
        w = window_s or (self.slo.slow_s if self.slo is not None
                         else 4.0 * self.window_s)
        now = time.time()
        with self._targets_lock:
            targets = list(self.targets.values())
        return {
            "WindowSeconds": w,
            "Targets": {
                ts.url: ts.dump_window(self._CAPSULE_FAMILIES, w, now)
                for ts in targets
            },
        }

    def up_targets(self) -> list[str]:
        """Scrape targets currently considered up — the capsule
        coordinator's fan-out set for cluster-scoped alerts."""
        now = time.time()
        with self._targets_lock:
            return [
                ts.url
                for ts in self.targets.values()
                if ts.last_success and ts.staleness(now) < self.stale_after
            ]

    def top_payload(self, n: int = 10) -> dict:
        """Busiest nodes by req/s (with 5xx rate and http p99) and
        biggest volumes by size — the cluster.top shell surface."""
        now = time.time()
        w = self.window_s
        with self._targets_lock:
            targets = list(self.targets.values())
        # QoS plane: heartbeat-reported live load per volume server
        # (the same numbers pick_for_write's power-of-two-choices uses)
        load_by_url = {
            dn.url: (dn.in_flight, dn.write_queue_depth)
            for dn in self.master.topology.data_nodes()
        }
        nodes = []
        for ts in targets:
            if not ts.last_success:
                continue
            total = ts.rate_sum("weed_http_request_total", w, now)
            errs = ts.rate_sum(
                "weed_http_request_total", w, now,
                label_filter=lambda l: l.get("status", "").startswith("5"),
            )
            p99 = ts.quantile("weed_http_request_seconds", 0.99, w, now)
            in_flight, queue_depth = load_by_url.get(ts.url, (None, None))
            nodes.append({
                "Url": ts.url,
                "Kind": ts.kind,
                "ReqPerSec": round(total, 3),
                "ErrPerSec": round(errs, 3),
                "P99Ms": None if p99 is None else round(p99 * 1000.0, 3),
                "InFlight": in_flight,
                "WriteQueueDepth": queue_depth,
            })
        nodes.sort(key=lambda r: -r["ReqPerSec"])
        volumes = []
        for dn in self.master.topology.data_nodes():
            for vid, info in list(dn.volumes.items()):
                volumes.append({
                    "VolumeId": vid,
                    "Node": dn.url,
                    "Collection": info.collection,
                    "SizeBytes": info.size,
                    "FileCount": info.file_count,
                })
        volumes.sort(key=lambda r: -r["SizeBytes"])
        return {"Nodes": nodes[:n], "Volumes": volumes[:n]}
