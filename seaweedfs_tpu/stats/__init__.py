from seaweedfs_tpu.stats.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    DEFAULT_REGISTRY,
    start_push_loop,
)
from seaweedfs_tpu.stats.duration_counter import DurationCounter

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "DEFAULT_REGISTRY",
    "DurationCounter",
    "start_push_loop",
]
