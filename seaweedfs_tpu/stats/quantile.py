"""Shared quantile estimators.

One implementation for every consumer that ranks latencies: the bench
stage breakdowns (bench.py `trace` / `migration` / `scrub` configs),
the telemetry plane's ring TSDB (cluster-wide p99 from scraped
histogram buckets), and weedload's log-bucketed latency histograms.
Before this module each site hand-rolled its own `sorted()[int(n*p)]`
with subtly different clamping — the estimators must agree or the
cluster dashboard and the bench lines argue about the same tail.
"""

from __future__ import annotations


def percentile(values: list[float], p: float) -> float:
    """Nearest-rank percentile of an UNSORTED sample list.

    `p` in [0, 1]. Uses the ceil-of-rank convention (the value at index
    ceil(p*n)-1 of the sorted sample, clamped into range) so p=1.0 is
    the max and p=0.0 the min; matches what bench.py historically
    reported within one rank. Raises ValueError on an empty sample —
    every call site has a real decision to make when there is no data,
    and a silent 0.0 would read as "fast"."""
    if not values:
        raise ValueError("percentile of empty sample")
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"percentile p={p} outside [0, 1]")
    ordered = sorted(values)
    # ceil(p * n) - 1, computed without floats' ceil import
    rank = int(p * len(ordered) + 0.9999999999) - 1
    return ordered[max(0, min(len(ordered) - 1, rank))]


def histogram_quantile(
    bounds: list[float] | tuple[float, ...],
    counts: list[float] | list[int],
    q: float,
) -> float:
    """Prometheus-style quantile from a cumulative-free bucket histogram.

    `bounds[i]` is the inclusive upper bound of bucket i; `counts[i]`
    the number of observations that landed in bucket i (NOT cumulative
    — callers holding Prometheus cumulative buckets take adjacent
    differences first). `counts` may carry one extra overflow bucket
    (observations above the last bound). Linear interpolation inside
    the winning bucket, the same estimate promQL's histogram_quantile
    produces; the overflow bucket reports its lower edge (no upper
    bound to interpolate toward). Returns 0.0 when the histogram is
    empty."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"histogram quantile q={q} outside [0, 1]")
    if len(counts) not in (len(bounds), len(bounds) + 1):
        raise ValueError(
            f"counts ({len(counts)}) must match bounds ({len(bounds)}) "
            "or carry one overflow bucket"
        )
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            if i >= len(bounds):  # overflow bucket: no upper bound
                return bounds[-1] if bounds else 0.0
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (target - cum) / c
            return lo + (hi - lo) * max(0.0, min(1.0, frac))
        cum += c
    # q == 1.0 with all mass in bounded buckets
    for i in range(len(counts) - 1, -1, -1):
        if counts[i] > 0:
            return bounds[min(i, len(bounds) - 1)]
    return 0.0
