"""Rolling per-interval request counters for status UIs.

Behavioral match of weed/stats/duration_counter.go: fixed-size rings of
per-second / per-minute / per-hour buckets whose sum gives "requests in
the last N"; the master/volume HTML UIs render these.
"""

from __future__ import annotations

import threading
import time


class _Ring:
    def __init__(self, slots: int, seconds_per_slot: float):
        self.slots = slots
        self.seconds_per_slot = seconds_per_slot
        self.counts = [0] * slots
        self.stamps = [0] * slots

    def add(self, now: float, amount: int) -> None:
        slot_id = int(now / self.seconds_per_slot)
        idx = slot_id % self.slots
        if self.stamps[idx] != slot_id:
            self.stamps[idx] = slot_id
            self.counts[idx] = 0
        self.counts[idx] += amount

    def total(self, now: float) -> int:
        slot_id = int(now / self.seconds_per_slot)
        return sum(
            c
            for c, s in zip(self.counts, self.stamps)
            if slot_id - s < self.slots
        )


class DurationCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._minute = _Ring(60, 1.0)       # last minute, per-second
        self._hour = _Ring(60, 60.0)        # last hour, per-minute
        self._day = _Ring(24, 3600.0)       # last day, per-hour
        self.total = 0

    def add(self, amount: int = 1, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self.total += amount
            self._minute.add(now, amount)
            self._hour.add(now, amount)
            self._day.add(now, amount)

    def snapshot(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            return {
                "total": self.total,
                "last_minute": self._minute.total(now),
                "last_hour": self._hour.total(now),
                "last_day": self._day.total(now),
            }
