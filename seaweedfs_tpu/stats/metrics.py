"""Prometheus-style metrics: registry, counters, gauges, histograms,
text exposition, and a push loop.

Behavioral match of weed/stats/metrics.go:14-60: the reference keeps
Gather-able registries per process (filer/volume), wraps every HTTP
handler and filer-store call in request counters + duration histograms,
and pushes to a push gateway on an interval configured by the master's
HeartbeatResponse (master_grpc_server.go:80-84, LoopPushingMetric).
Here: a Registry renders Prometheus text format 0.0.4 so any scraper
understands it; `start_push_loop` POSTs that text to a
pushgateway-style URL on an interval.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
import urllib.request

DEFAULT_BUCKETS = (
    0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0
)

# weedscope (docs/TELEMETRY.md): histogram bucket exemplars — each
# bucket remembers the LAST trace id observed into it, rendered
# OpenMetrics-style (`... # {trace_id="..."} v`) so a burning SLO links
# straight to a concrete trace. WEED_SCOPE=0 kills recording AND
# rendering (the exposition reverts to plain 0.0.4 text).
_EXEMPLARS_ENABLED = os.environ.get("WEED_SCOPE", "1") != "0"


def exemplars_enabled() -> bool:
    return _EXEMPLARS_ENABLED


def set_exemplars_enabled(on: bool) -> None:
    """Runtime toggle (bench A/B arms and tests flip this in-process)."""
    global _EXEMPLARS_ENABLED
    _EXEMPLARS_ENABLED = bool(on)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def labels(self, *label_values: str) -> "_CounterChild":
        return _CounterChild(self, tuple(label_values))

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(label_values), 0.0)

    def _add(self, key: tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items or [((), 0.0)]:
            labels = dict(zip(self.label_names, key))
            lines.append(f"{self.name}{_fmt_labels(labels)} {val}")
        return lines


class _CounterChild:
    def __init__(self, parent: Counter, key: tuple[str, ...]):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._parent._add(self._key, amount)


class Gauge:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[tuple(label_values)] = value

    def add(self, amount: float, *label_values: str) -> None:
        key = tuple(label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(label_values), 0.0)

    def remove(self, *label_values: str) -> None:
        """Drop one label row entirely. Gauges keyed by node/target URL
        grow a row per member ever seen; a departed node must DISAPPEAR
        from /metrics (telemetry/collector.py's dead-node TTL), not
        linger as a frozen 0.0 row forever on autoscaled fleets."""
        with self._lock:
            self._values.pop(tuple(label_values), None)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items or [((), 0.0)]:
            labels = dict(zip(self.label_names, key))
            lines.append(f"{self.name}{_fmt_labels(labels)} {val}")
        return lines


class Histogram:
    def __init__(
        self,
        name: str,
        help_: str,
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        # (label key, bucket idx) -> (trace_id, observed value): the
        # last exemplar per bucket (weedscope). Written only through
        # put_exemplar — observe() itself never pays for it, so the
        # untraced hot path is byte-identical to the pre-exemplar one.
        self._exemplars: dict[tuple[tuple[str, ...], int], tuple[str, float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *label_values: str) -> None:
        key = tuple(label_values)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def put_exemplar(
        self, value: float, trace_id: str, *label_values: str
    ) -> None:
        """Remember `trace_id` as the latest exemplar for the bucket
        `value` falls into. Callers that already hold a trace id (the
        dispatch funnel's traced branch, the span-ring drain, the C
        fast-path complete callback) call this AFTER observe(); it is
        deliberately not folded into observe() so untraced requests pay
        nothing."""
        if not _EXEMPLARS_ENABLED or not trace_id:
            return
        key = tuple(label_values)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._exemplars[(key, idx)] = (trace_id, value)

    def time(self, *label_values: str) -> "_Timer":
        return _Timer(self, label_values)

    def count(self, *label_values: str) -> int:
        return sum(self._counts.get(tuple(label_values), []))

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            # copy each counts LIST, not just the dict: observe()
            # mutates the per-child list in place from serving threads,
            # and a render iterating the live list can emit bucket
            # cumulative counts that disagree with the _count line it
            # writes a few lines later (non-monotone exposition that
            # trips real scrapers)
            items = sorted(
                (key, list(counts)) for key, counts in self._counts.items()
            )
            sums = dict(self._sums)
            exemplars = dict(self._exemplars) if _EXEMPLARS_ENABLED else {}
        for key, counts in items:
            labels = dict(zip(self.label_names, key))
            cum = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                lb = dict(labels, le=repr(bound))
                ex = exemplars.get((key, i))
                lines.append(
                    f"{self.name}_bucket{_fmt_labels(lb)} {cum}"
                    + (
                        f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                        if ex is not None
                        else ""
                    )
                )
            cum += counts[-1]
            lb = dict(labels, le="+Inf")
            ex = exemplars.get((key, len(self.buckets)))
            lines.append(
                f"{self.name}_bucket{_fmt_labels(lb)} {cum}"
                + (
                    f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                    if ex is not None
                    else ""
                )
            )
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {sums.get(key, 0.0)}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {cum}")
        return lines


class _Timer:
    def __init__(self, hist: Histogram, label_values: tuple[str, ...]):
        self._hist = hist
        self._labels = label_values

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._start, *self._labels)
        return False


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()
        self._prerender_hooks: list = []

    def add_prerender_hook(self, fn) -> None:
        """Register a callable run before every text exposition — lets
        a subsystem that aggregates lazily (the tracing plane drains
        its span ring into histograms off the hot path) flush right
        before a scrape or push sees the numbers."""
        with self._lock:
            self._prerender_hooks.append(fn)

    def counter(self, name: str, help_: str, label_names: tuple[str, ...] = ()) -> Counter:
        m = Counter(name, help_, label_names)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name: str, help_: str, label_names: tuple[str, ...] = ()) -> Gauge:
        m = Gauge(name, help_, label_names)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(
        self,
        name: str,
        help_: str,
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        m = Histogram(name, help_, label_names, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def render_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            hooks = list(self._prerender_hooks)
            metrics = list(self._metrics)
        for fn in hooks:
            fn()
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


DEFAULT_REGISTRY = Registry()

# NOTE: the seed port registered the reference's weed_request_total/
# weed_request_seconds/weed_volumes/weed_filer_store_* families here
# verbatim — but nothing in this tree ever wrote OR read them, so every
# /metrics exposition rendered constant-zero rows that looked like live
# instrumentation (and weed_request_* shadowed the real
# weed_http_request_* families below). weedlint's contract tier flags
# exactly this class (contract-metric-orphan); the dead families are
# gone, OPERATIONS.md round 11 has the story.

# --- request tracing & gateway instrumentation (docs/TRACING.md) ------------
# One family for EVERY FastHandler server (volume/master/filer/s3/webdav/
# worker), observed centrally in util/httpd.serve_connection — this is
# what closes the "S3 and WebDAV expose no metrics" gap: the gateways
# ride the same mini loop, so they get counters + histograms for free.
HTTP_REQUEST_COUNTER = DEFAULT_REGISTRY.counter(
    "weed_http_request_total",
    "requests served through the mini request loop",
    ("server", "method", "status"),
)
HTTP_REQUEST_HISTOGRAM = DEFAULT_REGISTRY.histogram(
    "weed_http_request_seconds",
    "request dispatch latency through the mini request loop",
    ("server", "method"),
)
SPAN_HISTOGRAM = DEFAULT_REGISTRY.histogram(
    "weed_span_seconds",
    "trace span durations by span name and plane (serve|scrub|repair|tier)",
    ("name", "plane"),
)

# --- push-loop health --------------------------------------------------------
# The push loop swallows OSError by design (a dead pushgateway must not
# hurt the server) — these gauges make that death visible on /metrics
# instead of silent: a scraper alerts on last-success age or up==0.
PUSH_LAST_SUCCESS = DEFAULT_REGISTRY.gauge(
    "weed_metrics_push_last_success_unix",
    "unix time of the last successful pushgateway POST",
    ("job",),
)
PUSH_UP = DEFAULT_REGISTRY.gauge(
    "weed_metrics_push_up",
    "1 when the most recent pushgateway POST succeeded, else 0",
    ("job",),
)
PUSH_FAILURES = DEFAULT_REGISTRY.counter(
    "weed_metrics_push_failures_total",
    "pushgateway POSTs that failed",
    ("job",),
)

# --- cluster telemetry plane (docs/TELEMETRY.md) ----------------------------
# Set by the master's leader-only collector: per-target scrape health
# and the alert rule engine's firing state, re-exported so any external
# scraper of the master inherits cluster aggregation + alerting.
SCRAPE_STALENESS = DEFAULT_REGISTRY.gauge(
    "weed_scrape_staleness_seconds",
    "seconds since the collector last scraped this target successfully",
    ("target",),
)
SCRAPE_UP = DEFAULT_REGISTRY.gauge(
    "weed_scrape_up",
    "1 when the most recent scrape of this target succeeded, else 0",
    ("target",),
)
ALERT_FIRING = DEFAULT_REGISTRY.gauge(
    "weed_alert_firing",
    "1 while this alert rule is firing for this target",
    ("alert", "target"),
)

# --- scrub & self-healing plane (docs/SCRUB.md) -----------------------------
SCRUB_SCANNED = DEFAULT_REGISTRY.counter(
    "weed_scrub_scanned_bytes_total",
    "bytes verified by the background scrubber",
    ("server", "kind"),  # kind: plain | ec
)
SCRUB_CORRUPTIONS = DEFAULT_REGISTRY.counter(
    "weed_scrub_corruptions_found_total",
    "corruption events found by the scrubber",
    ("server", "kind"),
)
SCRUB_ECC_FALLBACK = DEFAULT_REGISTRY.counter(
    "weed_scrub_ecc_fallback_total",
    "scrub sweeps that expected a .ecc sidecar but fell back to the "
    "full parity re-verify (sidecar missing or stale)",
    ("server", "reason"),  # reason: missing | stale
)
SCRUB_QUARANTINED = DEFAULT_REGISTRY.gauge(
    "scrub_quarantined_shards",
    "EC shards currently quarantined on this server",
    ("server",),
)
REPAIR_STARTED = DEFAULT_REGISTRY.counter(
    "weed_repair_started_total",
    "repairs launched by the master scheduler",
    ("kind",),  # kind: ec_rebuild | replicate | replace
)
REPAIR_SUCCEEDED = DEFAULT_REGISTRY.counter(
    "weed_repair_succeeded_total",
    "repairs completed by the master scheduler",
    ("kind",),
)
REPAIR_FAILED = DEFAULT_REGISTRY.counter(
    "weed_repair_failed_total",
    "repairs that errored (will back off and retry)",
    ("kind",),
)
TIME_TO_REPAIR = DEFAULT_REGISTRY.histogram(
    "weed_time_to_repair_seconds",
    "first detection of damage to verified repair",
    ("kind",),
    buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0, 14400.0),
)

# --- QoS / tail-latency plane (docs/QOS.md) ---------------------------------
# Hedged reads (client side): fired = second attempt launched after the
# adaptive delay; won = the hedge (not the primary) returned first;
# cancelled = the losing attempt's connection was torn down mid-flight.
HEDGE_FIRED = DEFAULT_REGISTRY.counter(
    "weed_hedge_fired_total",
    "hedged read second attempts launched after the adaptive delay",
)
HEDGE_WON = DEFAULT_REGISTRY.counter(
    "weed_hedge_won_total",
    "hedged reads where the second attempt beat the primary",
)
HEDGE_CANCELLED = DEFAULT_REGISTRY.counter(
    "weed_hedge_cancelled_total",
    "losing hedged-read attempts cancelled (connection torn down)",
)
HEDGE_SERVED = DEFAULT_REGISTRY.counter(
    "weed_hedge_served_total",
    "requests a server observed carrying the x-weed-hedge hop header",
    ("server",),
)
# --- EC degraded reads & repair-bandwidth accounting (docs/SCRUB.md) --------
# Every degraded/repair byte moved is counted so bench can report
# bytes-moved-per-rebuilt-byte and degraded-read p99 vs healthy p99.
EC_DEGRADED_READS = DEFAULT_REGISTRY.counter(
    "weed_ec_degraded_read_total",
    "EC intervals served by reconstruction (a shard was lost/quarantined)",
)
EC_TILE_CACHE = DEFAULT_REGISTRY.counter(
    "weed_ec_tile_cache_total",
    "reconstructed-tile cache probes on the degraded read path",
    ("result",),  # result: hit | miss
)
EC_REPAIR_BYTES_READ = DEFAULT_REGISTRY.counter(
    "weed_ec_repair_bytes_read_total",
    "survivor bytes gathered by EC rebuild, by where they came from",
    ("source",),  # source: local | remote
)
EC_REPAIR_BYTES_WRITTEN = DEFAULT_REGISTRY.counter(
    "weed_ec_repair_bytes_written_total",
    "rebuilt shard bytes written by EC rebuild",
)
EC_REPAIR_DONATED_BYTES = DEFAULT_REGISTRY.counter(
    "weed_ec_repair_donated_bytes_total",
    "tile bytes degraded serving handed to an in-progress rebuild",
)

ADMISSION_REJECTED = DEFAULT_REGISTRY.counter(
    "weed_admission_rejected_total",
    "requests shed with 503 + Retry-After by per-client admission control",
    ("server",),
)
GROUP_COMMIT_BATCHES = DEFAULT_REGISTRY.counter(
    "weed_group_commit_batches_total",
    "group-commit windows committed (one pwritev + one flush each)",
)
GROUP_COMMIT_WRITES = DEFAULT_REGISTRY.counter(
    "weed_group_commit_writes_total",
    "needle writes that rode a group-commit window",
)
COMMIT_FLUSHES = DEFAULT_REGISTRY.counter(
    "weed_commit_flush_total",
    "durability flushes (fsync) issued by the volume write path",
)

# --- robustness plane: unified retries + deadlines (docs/CHAOS.md) ----------
# The retry-amplification factor bench/chaos reports is
# weed_retry_total vs request volume; the budget gate shows up as
# weed_retry_budget_exhausted_total when a fault would have stormed.
RETRY_TOTAL = DEFAULT_REGISTRY.counter(
    "weed_retry_total",
    "retries granted by the unified RetryPolicy, by call-site label",
    ("site",),
)
RETRY_BUDGET_EXHAUSTED = DEFAULT_REGISTRY.counter(
    "weed_retry_budget_exhausted_total",
    "retries refused because the process-wide retry budget ran dry",
)
DEADLINE_REJECTED = DEFAULT_REGISTRY.counter(
    "weed_deadline_rejected_total",
    "requests 504-fast-rejected at dispatch: X-Weed-Deadline already expired",
    ("server",),
)

# --- weedguard health plane (docs/HEALTH.md) --------------------------------
# Master-side node state transitions (healthy/suspect/dead) and the
# volume-server hinted-handoff spool (written = a replica write was
# diverted into a durable hint; replayed = the handoff agent delivered
# it after heal; dropped = spool cap or unparseable hint).
HEALTH_TRANSITIONS = DEFAULT_REGISTRY.counter(
    "weed_health_transitions_total",
    "node health-state transitions observed by the master, by new state",
    ("state",),
)
HANDOFF_HINTS = DEFAULT_REGISTRY.counter(
    "weed_handoff_hints_total",
    "hinted-handoff events on the volume write path",
    ("event",),  # written | replayed | dropped
)

# --- lifecycle tiering + cross-cluster replication (docs/TIERING.md) --------
VOLUME_READS = DEFAULT_REGISTRY.counter(
    "weed_volume_read_total",
    "needle GETs served, per volume — the tier scheduler's "
    "access-temperature signal (scraped off the node by the collector)",
    ("volume",),
)
TIER_MOVES = DEFAULT_REGISTRY.counter(
    "weed_tier_moves_total",
    "EC volume tier transitions completed on this node",
    ("direction", "result"),  # direction: out | in; result: ok | error
)
TIER_BYTES = DEFAULT_REGISTRY.counter(
    "weed_tier_bytes_total",
    "shard bytes moved to/from the tier backend",
    ("direction",),  # out | in
)
TIER_REMOTE_READS = DEFAULT_REGISTRY.counter(
    "weed_tier_remote_read_total",
    "ranged sub-shard reads served from the tier backend",
)
TIER_REMOTE_READ_ERRORS = DEFAULT_REGISTRY.counter(
    "weed_tier_remote_read_errors_total",
    "tier backend reads that failed (the read degraded to "
    "peer-fetch/reconstruction instead)",
)
TIERED_VOLUMES = DEFAULT_REGISTRY.gauge(
    "weed_tiered_volumes",
    "EC volumes currently holding a remote tier attachment on this node",
    ("server",),
)
REPLICATION_LAG = DEFAULT_REGISTRY.gauge(
    "weed_replication_lag_events",
    "filer mutation events published but not yet consumed by the "
    "replication consumer group (logqueue depth)",
    ("group",),
)
REPLICATION_APPLIED = DEFAULT_REGISTRY.counter(
    "weed_replication_applied_total",
    "replicated filer events applied to the sink cluster",
    ("result",),  # ok | error | skipped
)
ARBITER_BYTES = DEFAULT_REGISTRY.counter(
    "weed_arbiter_bytes_total",
    "background bytes admitted by the bandwidth arbiter, per claimant",
    ("claimant",),  # rebuild | replication | handoff | tier
)
ARBITER_WAIT_SECONDS = DEFAULT_REGISTRY.counter(
    "weed_arbiter_wait_seconds_total",
    "seconds background claimants spent blocked on their share",
    ("claimant",),
)

# --- weedscope: SLO burn-rate engine + incident capsules --------------------
# Set by the leader's SLO engine (telemetry/slo.py) every collector
# cycle: multi-window burn rate per objective (window: fast | slow) and
# the fraction of the slow window's error budget still unspent.
SLO_BURN_RATE = DEFAULT_REGISTRY.gauge(
    "weed_slo_burn_rate",
    "error-budget burn rate per SLO objective and evaluation window "
    "(1.0 = burning exactly the sustainable budget)",
    ("objective", "window"),
)
SLO_BUDGET_REMAINING = DEFAULT_REGISTRY.gauge(
    "weed_slo_budget_remaining",
    "fraction of the SLO error budget left over the slow window "
    "(1.0 = untouched, 0.0 = fully burned)",
    ("objective",),
)
CAPSULE_CAPTURES = DEFAULT_REGISTRY.counter(
    "weed_capsule_captures_total",
    "incident capsules captured on this node",
    ("trigger",),  # trigger: alert | manual | error
)


# textual push-loop health (gauges can't carry the error STRING): job
# -> {"last_success_unix", "last_error"}; /cluster/health surfaces it
_push_status: dict[str, dict] = {}
_push_status_lock = threading.Lock()


def push_status() -> dict[str, dict]:
    """Per-job push-loop health rows for operator surfaces."""
    with _push_status_lock:
        return {job: dict(row) for job, row in _push_status.items()}


def start_push_loop(
    gateway_url: str,
    job: str,
    interval_sec: float,
    registry: Registry = DEFAULT_REGISTRY,
    stop_event: threading.Event | None = None,
) -> threading.Thread:
    """Push registry text to a pushgateway URL every interval
    (stats/metrics.go LoopPushingMetric; interval and address arrive in
    the master HeartbeatResponse in the reference)."""
    stop = stop_event or threading.Event()
    with _push_status_lock:
        _push_status[job] = {"last_success_unix": 0.0, "last_error": ""}

    def loop():
        while not stop.is_set():
            try:
                body = registry.render_text().encode()
                req = urllib.request.Request(
                    gateway_url.rstrip("/") + f"/metrics/job/{job}",
                    data=body,
                    method="POST",
                    headers={"Content-Type": "text/plain; version=0.0.4"},
                )
                urllib.request.urlopen(req, timeout=5).read()
                PUSH_LAST_SUCCESS.set(time.time(), job)
                PUSH_UP.set(1.0, job)
                with _push_status_lock:
                    _push_status[job] = {
                        "last_success_unix": round(time.time(), 3),
                        "last_error": "",
                    }
            except OSError as e:
                # push gateway being down must not hurt the server —
                # but it must be VISIBLE: /metrics carries the loop's
                # own health, and push_status() keeps the error string
                # for /cluster/health instead of failing silently
                PUSH_UP.set(0.0, job)
                PUSH_FAILURES.labels(job).inc()
                with _push_status_lock:
                    _push_status[job]["last_error"] = str(e)[:300]
            stop.wait(interval_sec)

    t = threading.Thread(target=loop, daemon=True, name="metrics-push")
    t.stop_event = stop
    t.start()
    return t
