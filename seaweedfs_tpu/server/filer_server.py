"""Filer server: HTTP namespace gateway + gRPC service.

Behavioral match of weed/server/filer_server*.go:

  * POST /path — assign a fid from the master, proxy the body to the
    volume server, create the entry; bodies over max_mb are split into
    chunks each under its own fid (filer_server_handlers_write.go:41,
    _write_autochunk.go:23 autoChunk);
  * GET /path — files stream their chunk views from volume servers
    (filer2/stream.go); directories list as JSON (readerAt the UI role);
  * DELETE /path?recursive=true — entry + async chunk GC;
  * gRPC — the 11-verb Filer service incl. AtomicRenameEntry inside a
    store transaction (filer_grpc_server.go, _rename.go).
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures
from urllib.parse import parse_qs, unquote, urlparse

import grpc

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.filer import filechunks, stream
from seaweedfs_tpu.filer.entry import Attr, Entry, normalize_path
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import EntryNotFound, new_store
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.util.httpd import FastHandler, WeedHTTPServer
from seaweedfs_tpu.pb import rpc


def _queue_publisher():
    """Default on_event: publish EventNotifications to the process
    notification queue when one is configured (filer_notify.go:9-39).
    Returns None when no queue is set so the filer skips the work."""
    from seaweedfs_tpu import notification

    if notification.queue is None:
        return None

    def publish(old, new, delete_chunks: bool) -> None:
        if notification.queue is None:  # queue torn down after start
            return
        key = (old or new).full_path
        msg = fpb.EventNotification(delete_chunks=delete_chunks)
        if old is not None:
            msg.old_entry.CopyFrom(old.to_pb())
        if new is not None:
            msg.new_entry.CopyFrom(new.to_pb())
            msg.new_parent_path = new.directory
        try:
            notification.queue.send_message(key, msg)
        except Exception as e:  # noqa: BLE001 — never fail the write
            # the entry is already durably stored; a broker hiccup must
            # not turn the client's POST into a 500 (filer_notify.go
            # logs SendMessage errors and continues). Matters since the
            # kafka queue does real network IO; embedded queues never
            # raised here.
            wlog.error("notify %s: %s", key, e)

    return publish


class FilerServer:
    def __init__(
        self,
        masters: list[str],
        host: str = "127.0.0.1",
        port: int = 8888,
        store: str = "memory",
        store_path: str = "",
        collection: str = "",
        replication: str = "",
        max_mb: int = 32,
        on_event=None,
        announce_interval: float = 10.0,
    ):
        self.masters = masters
        self.announce_interval = announce_interval
        self._announce: threading.Thread | None = None
        self._master_idx = 0  # rotates on failure (HA master failover)
        self.host = host
        self.port = port
        self.grpc_port = port + 10000
        self.collection = collection
        self.replication = replication
        self.max_mb = max_mb
        self.filer = Filer(
            new_store(store, store_path),
            masters,
            on_event=on_event or _queue_publisher(),
        )
        self._grpc_server: grpc.Server | None = None
        self._http_server: WeedHTTPServer | None = None

    # ------------------------------------------------------------------
    # master failover: any live master serves (non-leaders proxy writes
    # to the leader), so calls rotate through the seed list on failure
    def _with_master(self, fn):
        out, idx = op.with_master_failover(self.masters, fn, self._master_idx)
        self._master_idx = idx
        return out

    def _read_master(self, entry) -> str:
        """A master that can actually resolve this entry's chunks.

        Probes with a real LookupVolume of the first chunk's vid, so a
        follower with a stale leader pointer (which aborts UNAVAILABLE)
        rotates away BEFORE the 200 header goes out — a mid-stream
        lookup failure can only truncate the response. Success results
        are cached by op.lookup, so the steady-state cost is nil."""
        chunks = list(entry.chunks)
        if not chunks:
            return self.masters[self._master_idx % len(self.masters)]
        vid = chunks[0].fid.split(",")[0]

        def probe(m):
            res = op.lookup(m, vid)
            if res.error or not res.locations:
                # in-band leader answer ("volume not found"): do NOT
                # rotate — every master proxies to the same leader
                raise RuntimeError(
                    f"lookup {vid} via {m}: {res.error or 'no locations'}"
                )
            return m

        return self._with_master(probe)

    # ------------------------------------------------------------------
    # write path helpers
    def _assign(self, collection: str = "", replication: str = "", ttl: str = "") -> op.AssignResult:
        return self._with_master(
            lambda m: op.assign(
                m,
                collection=collection or self.collection,
                replication=replication or self.replication,
                ttl=ttl,
            )
        )

    def _upload_bytes(
        self, data: bytes, filename: str, mime: str, collection: str, replication: str, ttl: str
    ) -> list:
        """Upload `data` as 1..N chunks (autoChunk when over max_mb)."""
        chunk_size = self.max_mb * 1024 * 1024
        chunks = []
        offset = 0
        now_ns = time.time_ns()
        while True:
            piece = data[offset : offset + chunk_size] if chunk_size else data
            ar = self._assign(collection, replication, ttl)
            ur = op.upload(
                f"{ar.url}/{ar.fid}",
                piece,
                filename=filename,
                mime=mime,
                ttl=ttl,
                jwt=ar.auth,
            )
            if ur.error:
                raise RuntimeError(f"upload chunk: {ur.error}")
            chunks.append(
                filechunks.make_chunk(
                    ar.fid, offset, len(piece), now_ns + offset, e_tag=ur.etag
                )
            )
            offset += len(piece)
            if offset >= len(data):
                break
        return chunks

    # ------------------------------------------------------------------
    # gRPC servicer (filer_grpc_server.go)
    def LookupDirectoryEntry(self, req: fpb.LookupDirectoryEntryRequest, context):
        try:
            entry = self.filer.find_entry(f"{req.directory}/{req.name}")
        except EntryNotFound:
            context.abort(grpc.StatusCode.NOT_FOUND, f"{req.directory}/{req.name}")
        return fpb.LookupDirectoryEntryResponse(entry=entry.to_pb())

    def ListEntries(self, req: fpb.ListEntriesRequest, context):
        entries = self.filer.list_entries(
            req.directory,
            start_file_name=req.start_from_file_name,
            include_start=req.inclusive_start_from,
            limit=req.limit or 1024,
            prefix=req.prefix,
        )
        for e in entries:
            yield fpb.ListEntriesResponse(entry=e.to_pb())

    def CreateEntry(self, req: fpb.CreateEntryRequest, context):
        entry = Entry.from_pb(req.directory, req.entry)
        self.filer.create_entry(entry)
        return fpb.CreateEntryResponse()

    def UpdateEntry(self, req: fpb.UpdateEntryRequest, context):
        entry = Entry.from_pb(req.directory, req.entry)
        try:
            old = self.filer.find_entry(entry.full_path)
        except EntryNotFound:
            context.abort(grpc.StatusCode.NOT_FOUND, entry.full_path)
        garbage = filechunks.minus_chunks(old.chunks, entry.chunks)
        self.filer.update_entry(entry)
        if garbage:
            self.filer.delete_chunks_async([c.fid for c in garbage])
        return fpb.UpdateEntryResponse()

    def DeleteEntry(self, req: fpb.DeleteEntryRequest, context):
        try:
            self.filer.delete_entry(
                f"{req.directory}/{req.name}",
                is_recursive=req.is_recursive,
                delete_data=req.is_delete_data,
            )
        except EntryNotFound:
            pass
        except ValueError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return fpb.DeleteEntryResponse()

    def AtomicRenameEntry(self, req: fpb.AtomicRenameEntryRequest, context):
        try:
            self.filer.atomic_rename(
                f"{req.old_directory}/{req.old_name}",
                f"{req.new_directory}/{req.new_name}",
            )
        except EntryNotFound as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return fpb.AtomicRenameEntryResponse()

    def AssignVolume(self, req: fpb.AssignVolumeRequest, context):
        ar = self._assign(req.collection, req.replication)
        return fpb.AssignVolumeResponse(
            fid=ar.fid,
            url=ar.url,
            public_url=ar.public_url,
            count=ar.count,
            auth=ar.auth,
        )

    def LookupVolume(self, req: fpb.LookupVolumeRequest, context):
        out = fpb.LookupVolumeResponse()
        for vid in req.volume_ids:
            res = self._with_master(lambda m: op.lookup(m, vid))
            locs = out.locations_map[vid]
            for l in res.locations:
                locs.locations.add(url=l["url"], public_url=l["publicUrl"])
        return out

    def DeleteCollection(self, req: fpb.DeleteCollectionRequest, context):
        from seaweedfs_tpu.pb import master_pb2
        from seaweedfs_tpu.pb.rpc import grpc_address

        def call(m):
            with rpc.dial(grpc_address(m)) as ch:
                rpc.master_stub(ch).CollectionDelete(
                    master_pb2.CollectionDeleteRequest(name=req.collection)
                )

        self._with_master(call)
        return fpb.DeleteCollectionResponse()

    def Statistics(self, req: fpb.StatisticsRequest, context):
        from seaweedfs_tpu.pb import master_pb2
        from seaweedfs_tpu.pb.rpc import grpc_address

        def call(m):
            with rpc.dial(grpc_address(m)) as ch:
                return rpc.master_stub(ch).Statistics(
                    master_pb2.StatisticsRequest(
                        replication=req.replication,
                        collection=req.collection,
                        ttl=req.ttl,
                    )
                )

        resp = self._with_master(call)
        return fpb.StatisticsResponse(
            total_size=resp.total_size,
            used_size=resp.used_size,
            file_count=resp.file_count,
        )

    def GetFilerConfiguration(self, req, context):
        return fpb.GetFilerConfigurationResponse(
            masters=self.masters,
            replication=self.replication,
            collection=self.collection,
            max_mb=self.max_mb,
        )

    # ------------------------------------------------------------------
    # directory browser (server/filer_ui/templates.go role)
    def _render_dir_html(
        self, path: str, entries, limit: int, last: str, more: bool
    ) -> str:
        """Breadcrumbed directory listing for browsers, with the
        reference's lastFileName/limit load-more pagination link
        (filer_ui/templates.go, breadcrumb.go ToBreadcrumb)."""
        import html as _html
        import time as _time
        from urllib.parse import quote

        from seaweedfs_tpu.util.status_ui import status_page

        crumbs = ["<a href='/'>/</a>"]
        parts = [p for p in path.split("/") if p]
        for i, part in enumerate(parts):
            link = quote("/" + "/".join(parts[: i + 1]) + "/")
            crumbs.append(f"<a href='{link}'>{_html.escape(part)} /</a>")
        rows = []
        for e in entries:
            name = _html.escape(e.name)
            href = quote(e.full_path) + ("/" if e.is_directory else "")
            size = "" if e.is_directory else str(e.size())
            mtime = (
                _time.strftime(
                    "%Y-%m-%d %H:%M:%S", _time.localtime(e.attr.mtime)
                )
                if e.attr.mtime
                else ""
            )
            mime = "dir" if e.is_directory else _html.escape(e.attr.mime or "")
            rows.append(
                f"<tr><td><a href='{href}'>{name}</a></td>"
                f"<td>{size}</td><td>{mtime}</td><td>{mime}</td></tr>"
            )
        if more:
            next_link = (
                quote(path) + f"/?limit={limit}&lastFileName={quote(last)}"
                if path != "/"
                else f"/?limit={limit}&lastFileName={quote(last)}"
            )
            rows.append(
                f"<tr><td colspan=4><a href='{next_link}'>load more…</a>"
                "</td></tr>"
            )
        return status_page(
            "SeaweedFS-TPU Filer",
            " ".join(crumbs),
            f"{len(entries)} entries &middot; limit {limit}",
            ["Name", "Size", "Modified", "Type"],
            "".join(rows),
            ["/", "/metrics"],
            section_heading="Entries",
        )

    # ------------------------------------------------------------------
    # HTTP
    def _http_handler_class(self):
        server = self

        class Handler(FastHandler):
            # FastHandler rides WeedHTTPServer's mini request loop
            # (one-scan head parse, FastHeaders, body realignment —
            # util/httpd.serve_connection); the send_response/
            # send_header slow paths below are untouched

            def _reply(self, status, body=b"", headers=None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD" and body:
                    self.wfile.write(body)

            def _json(self, obj, status=200):
                self._reply(
                    status,
                    json.dumps(obj).encode(),
                    {"Content-Type": "application/json"},
                )

            def _path_and_query(self):
                url = urlparse(self.path)
                return (
                    normalize_path(unquote(url.path)),
                    {k: v[0] for k, v in parse_qs(url.query).items()},
                )

            def do_GET(self):
                path, q = self._path_and_query()
                try:
                    entry = server.filer.find_entry(path)
                except EntryNotFound:
                    return self._json({"error": "not found"}, 404)
                if entry.is_directory:
                    try:
                        limit = max(1, int(q.get("limit", "100")))
                    except ValueError:
                        limit = 100
                    # limit+1 fetch decides the pagination flag exactly
                    # (the reference's extra-entry trick) — no phantom
                    # load-more page on exact-multiple directories
                    entries = server.filer.list_entries(
                        path,
                        start_file_name=q.get("lastFileName", ""),
                        limit=limit + 1,
                    )
                    more = len(entries) > limit
                    entries = entries[:limit]
                    last = entries[-1].name if entries else q.get("lastFileName", "")
                    # browsers get the breadcrumbed HTML listing the
                    # reference renders (filer_ui/templates.go via
                    # filer_server_handlers_read_dir.go:16-45); API
                    # clients keep the JSON shape
                    if "text/html" in self.headers.get("Accept", ""):
                        return self._reply(
                            200,
                            server._render_dir_html(
                                path, entries, limit, last, more
                            ).encode(),
                            {"Content-Type": "text/html; charset=utf-8"},
                        )
                    return self._json(
                        {
                            "Path": path,
                            "Entries": [
                                {
                                    "FullPath": e.full_path,
                                    "IsDirectory": e.is_directory,
                                    "Size": e.size(),
                                    "Mtime": e.attr.mtime,
                                    "Mime": e.attr.mime,
                                }
                                for e in entries
                            ],
                            "Limit": limit,
                            "LastFileName": last,
                            "ShouldDisplayLoadMore": more,
                        }
                    )
                headers = {
                    "Content-Type": entry.attr.mime or "application/octet-stream",
                    "ETag": filechunks.etag(entry.chunks) if entry.chunks else "",
                }
                # entry.size() honors an explicit file_size (truncate
                # may clamp below the chunk total)
                total = entry.size()
                headers["Accept-Ranges"] = "bytes"
                status, offset, length = 200, 0, total
                from seaweedfs_tpu.util.http_range import (
                    RangeNotSatisfiable,
                    parse_range,
                )

                try:
                    span = parse_range(self.headers.get("Range", ""), total)
                except RangeNotSatisfiable:
                    self.send_response(416)
                    self.send_header("Content-Range", f"bytes */{total}")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if span is not None:
                    start, end = span
                    status, offset, length = 206, start, end - start + 1
                    headers["Content-Range"] = f"bytes {start}-{end}/{total}"
                # resolve a master that can serve the chunks BEFORE the
                # status line goes out: a probe failure here is a clean
                # 503, not a 200 with a truncated body
                try:
                    read_master = server._read_master(entry)
                except (RuntimeError, OSError, grpc.RpcError) as e:
                    return self._json({"error": str(e)}, 503)
                self.send_response(status)
                for k, v in headers.items():
                    if v:
                        self.send_header(k, v)
                self.send_header("Content-Length", str(length))
                self.end_headers()
                if self.command == "HEAD":
                    # size/etag come from metadata alone — no chunk I/O
                    return
                written = 0
                try:
                    for piece in stream.stream_content(
                        read_master, entry.chunks, offset, length
                    ):
                        self.wfile.write(piece)
                        written += len(piece)
                except (RuntimeError, OSError):
                    pass
                if written < length:
                    # failure or sparse hole after headers: truncate so
                    # the client sees a short read, not silent corruption
                    # (compare against the RESPONSE length — a completed
                    # 206 must keep the connection reusable)
                    self.close_connection = True

            do_HEAD = do_GET

            def do_POST(self):
                path, q = self._path_and_query()
                # normalize_path strips trailing slashes, so check the
                # raw URL to tell "POST /dir/" (mkdir) from "POST /dir"
                raw_path = unquote(urlparse(self.path).path)
                if "chunked" in self.headers.get(
                    "Transfer-Encoding", ""
                ).lower():
                    # chunked uploads (Go clients PUT unknown-length
                    # readers this way); an unread chunked body would
                    # desync the keep-alive connection
                    try:
                        data = self._read_chunked_body()
                    except ValueError as e:
                        self.close_connection = True
                        return self._json({"error": str(e)}, 400)
                    length = len(data)
                else:
                    length = int(self.headers.get("Content-Length", "0"))
                    data = self.rfile.read(length)
                mime = self.headers.get("Content-Type", "")
                upload_filename = ""
                if mime.lower().startswith("multipart/form-data"):
                    # `curl -F` form uploads (filer_server_handlers_write.go
                    # parses the same way through ParseUpload)
                    from seaweedfs_tpu.util.multipart import (
                        MalformedUpload,
                        parse_upload,
                    )

                    try:
                        p = parse_upload(data, mime)
                    except MalformedUpload as e:
                        return self._json({"error": str(e)}, 400)
                    data, mime = p.data, p.mime
                    upload_filename = p.filename
                    if upload_filename and raw_path.endswith("/"):
                        # form upload INTO a directory: store the file
                        # under its form filename, don't mkdir
                        path = f"{path.rstrip('/')}/{upload_filename}"
                        raw_path = path
                if (raw_path.endswith("/") and raw_path != "/") or (
                    not data and not length and self.command == "POST"
                ):
                    # mkdir (the reference creates dirs via FUSE/gRPC;
                    # HTTP POST with no body maps to mkdir here — but a
                    # zero-byte PUT means an EMPTY FILE, as everywhere)
                    from seaweedfs_tpu.filer.entry import new_directory_entry

                    server.filer.create_entry(new_directory_entry(path))
                    return self._json({"name": path}, 201)
                try:
                    chunks = server._upload_bytes(
                        data,
                        filename=upload_filename or path.rsplit("/", 1)[-1],
                        mime=mime,
                        collection=q.get("collection", ""),
                        replication=q.get("replication", ""),
                        ttl=q.get("ttl", ""),
                    )
                except RuntimeError as e:
                    return self._json({"error": str(e)}, 500)
                now = int(time.time())
                entry = Entry(
                    full_path=path,
                    attr=Attr(
                        mtime=now,
                        crtime=now,
                        mime=mime,
                        replication=q.get("replication", ""),
                        collection=q.get("collection", ""),
                    ),
                    chunks=chunks,
                )
                server.filer.create_entry(entry)
                self._json({"name": entry.name, "size": len(data)}, 201)

            def do_DELETE(self):
                path, q = self._path_and_query()
                try:
                    server.filer.delete_entry(
                        path,
                        is_recursive=q.get("recursive") == "true",
                        delete_data=True,
                    )
                except EntryNotFound:
                    return self._json({"error": "not found"}, 404)
                except ValueError as e:
                    return self._json({"error": str(e)}, 409)
                self._reply(204)

            def _read_chunked_body(self, limit=1 << 30) -> bytes:
                pieces = []
                total = 0
                while True:
                    szline = self.rfile.readline(1026).strip()
                    try:
                        size = int(szline.split(b";")[0], 16)
                    except ValueError:
                        raise ValueError(f"bad chunk size {szline[:32]!r}")
                    if size == 0:
                        while True:  # trailers until blank line
                            t = self.rfile.readline(65537)
                            if t in (b"\r\n", b"\n", b""):
                                break
                        return b"".join(pieces)
                    total += size
                    if total > limit:
                        raise ValueError("chunked body too large")
                    piece = self.rfile.read(size)
                    if len(piece) != size:
                        raise ValueError("truncated chunk")
                    pieces.append(piece)
                    self.rfile.readline(3)  # CRLF after each chunk

            # the reference routes PUT through the same PostHandler
            # (filer_server_handlers.go:25-28)
            do_PUT = do_POST

        return Handler

    # ------------------------------------------------------------------
    def start(self) -> None:
        self.filer.start_deletion_loop()
        self._grpc_server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._grpc_server.add_generic_rpc_handlers(
            (rpc.servicer_handler(rpc.FILER_SERVICE, rpc.FILER_METHODS, self),)
        )
        rpc.add_port(self._grpc_server, f"{self.host}:{self.grpc_port}")
        self._grpc_server.start()
        self._http_server = WeedHTTPServer(
            (self.host, self.port), self._http_handler_class()
        )
        # tracing plane: filer spans carry the gateway's trace onward to
        # the volume hops (assign/upload ride op.http_call, which
        # injects the header)
        self._http_server.trace_name = "filer"
        self._http_server.trace_node = f"{self.host}:{self.port}"
        # /metrics exposition via the mini loop (like S3/WebDAV): the
        # filer's UI always linked /metrics but its path router treated
        # it as a namespace lookup (404 on a fresh store) — the cluster
        # collector needs the real exposition. Tradeoff: a stored FILE
        # literally named /metrics is shadowed on GET, same contract as
        # the other gateways.
        self._http_server.gateway_metrics = True
        threading.Thread(target=self._http_server.serve_forever, daemon=True).start()
        # telemetry plane: announce this gateway to the master so the
        # leader's collector scrapes it, and start the sampling profiler
        from seaweedfs_tpu.telemetry import profiler
        from seaweedfs_tpu.telemetry.announce import start_announce_loop

        profiler.ensure_started()
        self._announce = start_announce_loop(
            "filer", f"{self.host}:{self.port}", self.masters,
            interval=self.announce_interval,
        )
        # replication plane (docs/TIERING.md): surface the producer's
        # view of consumer lag on THIS filer's /metrics — depth of the
        # "replicate" consumer group in the notification queue. The
        # collector scrapes it and RULE_REPL_LAG alerts on it; sampled
        # lazily at render time so an idle filer pays nothing.
        from seaweedfs_tpu import notification
        from seaweedfs_tpu.stats.metrics import (
            DEFAULT_REGISTRY,
            REPLICATION_LAG,
        )

        def _sample_repl_lag() -> None:
            q = notification.queue
            depth = getattr(q, "depth", None)
            if callable(depth):
                try:
                    REPLICATION_LAG.set(depth("replicate"), "replicate")
                except OSError:
                    pass

        # process-global registry + process-global notification.queue:
        # one hook regardless of how many filers this process embeds
        if not getattr(DEFAULT_REGISTRY, "_repl_lag_hooked", False):
            DEFAULT_REGISTRY._repl_lag_hooked = True
            DEFAULT_REGISTRY.add_prerender_hook(_sample_repl_lag)

    def stop(self) -> None:
        if self._announce is not None:
            self._announce.stop_event.set()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        self.filer.stop()
