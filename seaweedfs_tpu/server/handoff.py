"""Durable hinted handoff for replica writes (docs/HEALTH.md).

Before weedguard, a replicated write was all-or-error: one down/suspect
replica failed the whole POST even though the primary had durably
applied it. That couples write availability to the worst replica —
exactly what the health plane exists to decouple.

Now, when a replica hop fails (and the health plane is on), the primary
persists the complete replica request as a **hint** — method, target
path+query (already carrying `type=replicate` so the peer stores
without re-fanning), the replicated header subset, and the raw body —
in a per-target spool under its data directory, acks the client, and a
background handoff agent replays the spool in order once the replica
answers again.

Durability contract (audited by the weedcrash enumerator sweep,
tests/test_health.py): the hint is published with `util/durable`
(write tmp → fsync → rename → dirsync) BEFORE the client is acked, so
"acked with a hint" survives a primary crash; replay after the crash
delivers the same bytes, and replaying twice is idempotent on the
replica (the needle write path dedups identical records — see
Volume._is_file_unchanged). Hints are deleted only after a 2xx from
the replica, with the spool directory fsynced so the deletion sticks.

`WEED_HANDOFF=0` disables hinting alone (replica failures fail the
write, pre-health behavior); `WEED_HEALTH=0` implies it.
`WEED_HANDOFF_MAX_MB` caps each target's spool — a full spool refuses
the hint and the write fails loudly, never silently dropping data the
client was about to be promised.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time

from seaweedfs_tpu.util import durable, wlog

_HDR = struct.Struct(">I")  # header-JSON length prefix

# replica-relevant request headers, the same set replicate_to_peers
# forwards (per-needle semantics must survive the detour byte-for-byte);
# seaweed-* prefixed pairs ride too — see keep_headers()
KEEP_HEADERS = ("content-type", "content-encoding", "authorization")


def keep_headers(headers) -> dict[str, str]:
    """The header subset a hint must preserve — the ONE home for the
    rule (the volume server's fan-out seam routes here)."""
    out: dict[str, str] = {}
    for hk, hv in headers.items():
        lk = hk.lower()
        if lk in KEEP_HEADERS or lk.startswith("seaweed-"):
            out[hk] = hv
    return out


def handoff_enabled() -> bool:
    """Hinting on? Requires the health plane; WEED_HANDOFF=0 turns the
    handoff leg off by itself for A/B runs."""
    from seaweedfs_tpu.cluster import health as _health

    if not _health.enabled():
        return False
    return os.environ.get("WEED_HANDOFF", "1") != "0"


def spool_cap_bytes() -> int:
    """Per-target spool bound (WEED_HANDOFF_MAX_MB, default 256)."""
    try:
        mb = int(os.environ.get("WEED_HANDOFF_MAX_MB", "256"))
    except ValueError:
        mb = 256
    return mb << 20


def _target_dir(root: str, target: str) -> str:
    # "host:port" → filesystem-safe component
    return os.path.join(root, target.replace(":", "_").replace("/", "_"))


def _target_of_dir(name: str) -> str:
    host, _, port = name.rpartition("_")
    return f"{host}:{port}" if port.isdigit() else name


class HintStore:
    """The on-disk spool: one directory per unreachable target, one
    file per hinted request, ordered by filename (timestamp + seq) so
    replay preserves the primary's apply order per target."""

    def __init__(self, root: str):
        self.root = root
        self._seq = 0
        self._lock = threading.Lock()

    def _next_name(self) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return "%013d-%06d.hint" % (int(time.time() * 1000), seq)

    def _dir_size(self, tdir: str) -> int:
        try:
            return sum(
                e.stat().st_size
                for e in os.scandir(tdir)
                if e.name.endswith(".hint")
            )
        except OSError:
            return 0

    def write_hint(
        self,
        target: str,
        method: str,
        path: str,
        body: bytes,
        headers: dict[str, str],
    ) -> bool:
        """Durably spool one replica request; False = refused (spool
        over cap or unwritable) — the caller must then fail the write
        like the pre-handoff code did."""
        tdir = _target_dir(self.root, target)
        try:
            os.makedirs(tdir, exist_ok=True)
            if self._dir_size(tdir) + len(body) > spool_cap_bytes():
                from seaweedfs_tpu.stats.metrics import HANDOFF_HINTS

                HANDOFF_HINTS.labels("dropped").inc()
                wlog.error(
                    "handoff: spool for %s over cap; refusing hint", target
                )
                return False
            head = json.dumps(
                {"target": target, "method": method, "path": path,
                 "headers": headers}
            ).encode()
            name = self._next_name()
            final = os.path.join(tdir, name)
            tmp = final + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_HDR.pack(len(head)))
                f.write(head)
                f.write(body)
            # the durable publish IS the ack gate: fsync bytes, rename
            # to *.hint, fsync the spool dir — a crash on the primary
            # leaves either no hint (write not yet acked) or a complete
            # one (acked; the agent replays it after restart)
            durable.publish(tmp, final)
        except OSError as e:
            wlog.error("handoff: could not spool hint for %s: %s", target, e)
            return False
        from seaweedfs_tpu.stats.metrics import HANDOFF_HINTS

        HANDOFF_HINTS.labels("written").inc()
        return True

    def read_hint(self, path: str) -> tuple[dict, bytes] | None:
        """(header, body), or None for a torn/alien file (skipped and
        removed by the agent — the durable publish makes torn hints a
        can't-happen, but a spool must never wedge on one)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
            (hlen,) = _HDR.unpack_from(raw, 0)
            head = json.loads(raw[4 : 4 + hlen])
            return head, raw[4 + hlen :]
        except (OSError, ValueError, struct.error):
            return None

    def pending(self) -> dict[str, int]:
        """target → queued hint count (the /status + test surface)."""
        out: dict[str, int] = {}
        try:
            entries = os.scandir(self.root)
        except OSError:
            return out
        for e in entries:
            if not e.is_dir():
                continue
            try:
                n = sum(
                    1 for h in os.scandir(e.path) if h.name.endswith(".hint")
                )
            except OSError:
                n = 0
            if n:
                out[_target_of_dir(e.name)] = n
        return out

    def targets(self) -> list[tuple[str, str]]:
        """[(target, dir)] for every spool directory with hints."""
        out = []
        try:
            entries = sorted(os.scandir(self.root), key=lambda e: e.name)
        except OSError:
            return out
        for e in entries:
            if e.is_dir():
                out.append((_target_of_dir(e.name), e.path))
        return out

    def remove(self, path: str) -> None:
        try:
            os.remove(path)
            durable.fsync_dir(os.path.dirname(path))
        except OSError:
            pass


class HandoffAgent:
    """Background replayer: wakes every `interval`, and for each target
    with spooled hints replays them in filename (arrival) order through
    the pooled HTTP plane. A transport failure or 5xx stops that
    target's run for this round (the replica is still sick); 2xx — and
    404 for DELETEs, the idempotent no-op — deliver the hint."""

    def __init__(self, store: HintStore, interval: float = 1.0, sign=None):
        self.store = store
        self.interval = interval
        # `sign(fid) -> Authorization value` re-signs replays on signed
        # clusters: the CLIENT's write JWT spooled in the hint expires
        # on token timescales while the outage can last longer — a
        # stale token would 401 every replay and wedge the spool (the
        # replica silently diverging from the acked primary). The
        # server signs its own token, exactly like the delete cascade.
        self.sign = sign
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self.replayed = 0  # lifetime, for tests/status

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="weed-handoff"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def trigger(self) -> None:
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — the agent must survive
                import traceback

                wlog.warning(
                    "handoff: replay cycle crashed: %s",
                    traceback.format_exc(),
                )

    def run_once(self) -> int:
        """One replay pass over every target; returns hints delivered.
        Also the synchronous seam tests and drain paths drive."""
        delivered = 0
        for target, tdir in self.store.targets():
            try:
                names = sorted(
                    e.name
                    for e in os.scandir(tdir)
                    if e.name.endswith(".hint")
                )
            except OSError:
                continue
            for name in names:
                if self._stop.is_set():
                    return delivered
                path = os.path.join(tdir, name)
                parsed = self.store.read_hint(path)
                if parsed is None:
                    from seaweedfs_tpu.stats.metrics import HANDOFF_HINTS

                    HANDOFF_HINTS.labels("dropped").inc()
                    self.store.remove(path)
                    continue
                head, body = parsed
                # pace the replay through the shared bandwidth arbiter
                # BEFORE moving the bytes: a big spool used to replay at
                # full speed against a concurrent rebuild (the known gap
                # ROADMAP named) — now it gets the handoff claimant's
                # share and yields to foreground serving
                from seaweedfs_tpu.scrub.arbiter import get_arbiter

                if not get_arbiter().take(
                    "handoff", max(len(body), 1), stop=self._stop
                ):
                    return delivered  # stopping: bytes were never sent
                verdict = self._replay(head, body)
                if verdict == "sick":
                    break  # target still sick: keep order, retry later
                if verdict == "reject":
                    # the target is UP and says no (4xx: volume moved
                    # off it, auth revoked): retrying cannot change the
                    # verdict, and blocking the queue behind it would
                    # wedge every deliverable hint for this target —
                    # drop it loudly; the repair/replication planes own
                    # replica convergence from here
                    from seaweedfs_tpu.stats.metrics import HANDOFF_HINTS

                    HANDOFF_HINTS.labels("dropped").inc()
                    self.store.remove(path)
                    continue
                # count BEFORE removing the spool file: pending()
                # draining to empty is the barrier observers (the
                # /status surface, tests) synchronize on, so the
                # counters must already reflect a delivery by the time
                # the last file disappears — the old order let a
                # descheduled agent thread show "spool empty,
                # 0 replayed" to a racing reader
                delivered += 1
                self.replayed += 1
                from seaweedfs_tpu.stats.metrics import HANDOFF_HINTS

                HANDOFF_HINTS.labels("replayed").inc()
                self.store.remove(path)
        return delivered

    def _replay(self, head: dict, body: bytes) -> str:
        """One delivery attempt: "done" (delivered / nothing left to
        deliver), "sick" (transport failure or 5xx — the target is
        still down, retry later), or "reject" (a live target refused
        with a 4xx — permanent for this hint)."""
        from seaweedfs_tpu.client.operation import http_call

        method = head.get("method", "POST")
        path = head["path"]
        url = f"{head['target']}{path}"
        headers = dict(head.get("headers") or {})
        if self.sign is not None:
            fid = path.lstrip("/").partition("?")[0]
            headers["Authorization"] = self.sign(fid)
        try:
            status, _, _ = http_call(
                method,
                url,
                body=body if method == "POST" else None,
                headers=headers,
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001 — unreachable target, retried
            wlog.info("handoff: %s still unreachable: %s", head["target"], e)
            return "sick"
        if status < 300 or (method == "DELETE" and status == 404):
            return "done"
        if status == 409:
            # CookieMismatch on replay: the record already landed with
            # these exact bytes in an earlier, half-acked delivery (or
            # was legitimately overwritten since). Retrying forever
            # cannot change the verdict — count it delivered.
            return "done"
        wlog.warning(
            "handoff: %s answered %d for a hint (%s %s)",
            head["target"], status, method, path,
        )
        return "sick" if status >= 500 else "reject"
