"""Volume server: the data plane.

Behavioral match of the reference volume server
(weed/server/volume_server*.go, volume_grpc_*.go):

  * HTTP blob path — POST /<vid>,<fid> (multipart or raw body) with
    replication fan-out to replica peers guarded by ?type=replicate,
    GET/HEAD with cookie check, ETag/If-None-Match 304, EC fallback,
    DELETE with cookie check and replicated fan-out
    (volume_server_handlers_read.go:30, _write.go:19,
    topology/store_replicate.go:21);
  * gRPC admin plane — allocate/delete/mark-readonly/vacuum 4-phase/
    batch delete/copy file streams and the EC verb set
    (Generate/Rebuild/Copy/Mount/Unmount/Read/BlobDelete/ToVolume,
    volume_grpc_erasure_coding.go);
  * heartbeat client — background stream to the master pushing
    full-state inventories, following size-limit config
    (volume_grpc_client_to_master.go:24).

Degraded EC reads fetch missing shard intervals from peer volume
servers located via the master's LookupEcVolume, riding the same
VolumeEcShardRead stream the reference uses (store_ec.go:279).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from concurrent import futures
from urllib.parse import parse_qs

import grpc

from seaweedfs_tpu import qos, trace
from seaweedfs_tpu.scrub.arbiter import get_arbiter
from seaweedfs_tpu.stats.metrics import VOLUME_READS
from seaweedfs_tpu.util import deadline as _op_deadline
from seaweedfs_tpu.util import native_serve as _native_serve
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.ec_volume import EcVolume, NotEnoughShards
from seaweedfs_tpu.pb import master_pb2, rpc, volume_pb2 as pb
from seaweedfs_tpu.util.httpd import (
    JSON_HDR as _JSON_HDR,
    FastHandler,
    WeedHTTPServer,
    etag_matches,
    fast_query,
)

from seaweedfs_tpu.server import write_path
from seaweedfs_tpu.storage.file_id import FileId, parse_path_fid, parse_url_path
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NeedleNotFound,
    VolumeReadOnly,
    volume_base_name,
)

_esc_json = functools.lru_cache(maxsize=2048)(json.dumps)


@functools.lru_cache(maxsize=4096)
def _http_date(ts: int) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


COPY_CHUNK = 1024 * 1024


def _needle_manifest_bytes(n: Needle) -> bytes:
    """A chunk manifest's JSON, decompressed per the needle's gzip flag
    (operation.LoadChunkManifest(n.Data, n.IsGzipped()) role): manifests
    are text, so the write path's transparent compression applies."""
    data = bytes(n.data)
    if n.is_gzipped():
        from seaweedfs_tpu.util.compression import try_gunzip

        return try_gunzip(data)
    return data


def _parse_manifest_chunks(data: bytes) -> list[dict] | None:
    """Validate + sort a chunk manifest's chunk list; None if malformed.
    Manifests are client-supplied JSON, so every field is checked."""
    try:
        manifest = json.loads(data)
        chunks = manifest["chunks"]
        for c in chunks:
            if not isinstance(c["fid"], str):
                return None
            c["offset"] = int(c["offset"])
            c["size"] = int(c["size"])
        return sorted(chunks, key=lambda c: c["offset"])
    except (ValueError, KeyError, TypeError):
        return None


def make_needle_plan_core():
    """Build the per-needle fast-path plan closure shared by the lead's
    resolver and every worker's (docs/SERVING.md) — ONE implementation
    of "map a live needle record to a pre-rendered response", so the
    lead, the SO_REUSEPORT read workers, and the threaded do_GET arm
    can never drift apart on bytes.

    plan(v, fid, rng, head_only, gen, cacheable) takes a storage
    Volume `v` whose map view the caller has already refreshed, and
    returns:

      None          decline — semantics only the threaded handler has
                    (gzip/ttl/pairs/manifest flags, torn records,
                    .idx/.dat disagreement, remote-tier volumes)
      ("notfound",) missing/tombstoned needle — the caller maps it to
                    ITS 404 body (lead: empty, workers: JSON)
      ("cookie",)   cookie mismatch; distinct because the workers'
                    threaded arm serves a different 404 body for it
                    (the lead serves the same empty 404 for both)
      ("plan", t)   a widened 10-tuple (status, prefix, body, fd, off,
                    count, etag, prefix304, gen, cacheable) ready for
                    the C loop: etag/prefix304 let it answer
                    If-None-Match with a 304, gen/cacheable feed the
                    fd/offset plan cache

    Eligibility is wide (this PR): name/mime/last-modified flagged
    needles render Content-Type / Content-Disposition / Last-Modified
    exactly as do_GET does for a bare /<vid>,<fid> URL (no query
    string reaches here, so dl= and resize params can't)."""
    import os as _os
    from mimetypes import types_map as _types_map
    from os.path import splitext as _splitext

    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.needle import (
        FLAG_HAS_LAST_MODIFIED_DATE as _F_LM,
        FLAG_HAS_MIME as _F_MIME,
        FLAG_HAS_NAME as _F_NAME,
        get_actual_size as _actual_size,
    )
    from seaweedfs_tpu.util.crc import crc32c as _crc32c, masked_value as _masked
    from seaweedfs_tpu.util.http_range import (
        RangeNotSatisfiable,
        parse_range,
    )
    from seaweedfs_tpu.util.httpd import reply_prefix

    tomb = t.TOMBSTONE_FILE_SIZE
    pread = _os.pread
    dup = _os.dup
    # records at or under this take the one-pread in-memory path
    # (CRC verified, no fd duplication); larger go sendfile
    small = 65536
    octet_prefix = b"application/octet-stream"
    allowed = _F_NAME | _F_MIME | _F_LM
    prefix_304 = reply_prefix(304)

    def plan(v, fid, rng, head_only, gen, cacheable):
        with v._lock:
            fd = v._fd
            if fd is None:
                return None  # remote-tier volume
            nv = v.nm.get(fid.key)
            if nv is None or nv.offset == 0 or nv.size == tomb:
                return ("notfound",)
            size = nv.size
            if size < 5:
                return None  # v2/v3 body is at least data_size+flags
            off0 = nv.actual_offset
            rec_len = _actual_size(size, v.version)
            body_fd = -1
            if rec_len <= small:
                blob = pread(fd, rec_len, off0)
                if len(blob) < 20 + size + 4:
                    return None  # torn record: Python raises loudly
            else:
                blob = pread(fd, 20, off0)
                if len(blob) < 20:
                    return None
                body_fd = fd  # dup'd below once the record checks out
            if blob[12:16] != size.to_bytes(4, "big"):
                return None  # .idx/.dat disagree: Python path decides
            if int.from_bytes(blob[0:4], "big") != fid.cookie:
                return ("cookie",)  # CookieMismatch serves 404
            data_len = int.from_bytes(blob[16:20], "big")
            meta_len = size - 4 - data_len
            if meta_len < 1:
                return None
            if body_fd < 0:
                tail = blob[20 + data_len : 16 + size + 4]
            else:
                tail = pread(fd, meta_len + 4, off0 + 20 + data_len)
                if len(tail) < meta_len + 4:
                    return None
            flags = tail[0]
            if flags & ~allowed:
                return None  # gzip/ttl/pairs/manifest
            # incremental meta walk mirroring needle._parse_body_v2;
            # every meta byte must be accounted for, or this record is
            # not what the parser thinks it is
            pos = 1
            name = mime = b""
            lm = 0
            if flags & _F_NAME:
                if pos >= meta_len:
                    return None
                ln = tail[pos]
                pos += 1
                if pos + ln > meta_len:
                    return None
                name = bytes(tail[pos : pos + ln])
                pos += ln
            if flags & _F_MIME:
                if pos >= meta_len:
                    return None
                ln = tail[pos]
                pos += 1
                if pos + ln > meta_len:
                    return None
                mime = bytes(tail[pos : pos + ln])
                pos += ln
            if flags & _F_LM:
                if pos + 5 > meta_len:
                    return None
                lm = int.from_bytes(tail[pos : pos + 5], "big")
                pos += 5
            if pos != meta_len:
                return None
            stored = int.from_bytes(tail[meta_len : meta_len + 4], "big")
            if body_fd < 0:
                data = blob[20 : 20 + data_len]
                crc = _crc32c(data)
                if _masked(crc) != stored:
                    return None  # corrupt: the Python read raises
            else:
                data = None
                # ETag is the RAW crc; the trailer stores the
                # LevelDB-masked value — rotl17+const, so invert
                rot = (stored - 0xA282EAD8) & 0xFFFFFFFF
                crc = ((rot >> 17) | (rot << 15)) & 0xFFFFFFFF
                body_fd = dup(fd)
                # the dup keeps the CURRENT .dat alive for the
                # sendfile even if a vacuum commit swaps the
                # volume's fd before the response drains
        etag = f'"{crc:08x}"'
        headers = {"ETag": etag, "Content-Type": "application/octet-stream"}
        # header assembly order mirrors do_GET's dict insertion for a
        # bare fid URL: Content-Type override, Content-Disposition,
        # Last-Modified, Accept-Ranges, then a Content-Range
        fname = name.decode("latin-1") if name else ""
        if mime and not mime.startswith(octet_prefix):
            headers["Content-Type"] = mime.decode("latin-1")
        elif fname:
            ext = _splitext(fname)[1]
            guessed = _types_map.get(ext.lower()) if ext else None
            if guessed:
                headers["Content-Type"] = guessed
        if fname:
            escaped = fname.replace("\\", "\\\\").replace('"', '\\"')
            headers["Content-Disposition"] = f'inline; filename="{escaped}"'
        if flags & _F_LM:
            headers["Last-Modified"] = _http_date(lm)
        headers["Accept-Ranges"] = "bytes"
        etag_b = etag.encode()
        if rng:
            try:
                span = parse_range(rng.strip(), data_len)
            except RangeNotSatisfiable:
                if body_fd >= 0:
                    _os.close(body_fd)
                return ("plan", (
                    416,
                    reply_prefix(
                        416, {"Content-Range": f"bytes */{data_len}"}
                    ),
                    b"", -1, 0, 0,
                    etag_b, prefix_304, gen, 0,
                ))
            if span is not None:
                start, end = span
                headers["Content-Range"] = f"bytes {start}-{end}/{data_len}"
                if data is not None:
                    return ("plan", (
                        206, reply_prefix(206, headers),
                        data[start : end + 1], -1, 0, 0,
                        etag_b, prefix_304, gen, 0,
                    ))
                return ("plan", (
                    206, reply_prefix(206, headers), None,
                    body_fd, off0 + 20 + start, end - start + 1,
                    etag_b, prefix_304, gen, 0,
                ))
        if data is not None:
            return ("plan", (
                200, reply_prefix(200, headers), data, -1, 0, 0,
                etag_b, prefix_304, gen, cacheable,
            ))
        return ("plan", (
            200, reply_prefix(200, headers), None,
            body_fd, off0 + 20, data_len,
            etag_b, prefix_304, gen, cacheable,
        ))

    return plan


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        host: str = "127.0.0.1",
        port: int = 8080,
        master: str = "",
        public_url: str = "",
        data_center: str = "",
        rack: str = "",
        max_volume_counts: list[int] | None = None,
        heartbeat_interval: float = 2.0,
        read_redirect: bool = False,
        guard=None,
        ec_codec: str = "",
        storage_backends: dict | None = None,
        fix_jpg_orientation: bool = True,
        needle_map_kind: str = "memory",
        reuse_port: bool = False,
        internal_port: int = 0,
        shard_writes: bool = False,
        n_writers: int = 1,
        scrub_interval: float = 600.0,
        scrub_rate_mb_s: float = 64.0,
        serve_idle_ms: int = 0,
        serve_max_reqs: int = 0,
        commit_window_us: int = 0,
        commit_bytes: int = 4 << 20,
        commit_batch: int = 64,
        commit_fsync: bool = False,
        admission_rate: float = 0.0,
        admission_burst: float = 0.0,
        admission_inflight: int = 0,
        admission_procs: int = 1,
        admission_shm_path: str = "",
        announce: str = "",
    ):
        # `ec.codec` config: "cpu" | "native" | "tpu" | "" (auto: tpu
        # with a JAX device, else the native SIMD shim, else numpy).
        # Threaded into every server-side EC code
        # path — generate (ec_encoder.go:173 enc.Encode), rebuild, decode
        # back to a volume, and degraded-read reconstruction
        # (store_ec.go:364 enc.ReconstructData).
        self.ec_codec = ec_codec or None
        if storage_backends:
            # remote-tier backends (storage.backend config tree; the
            # reference ships this from master config in heartbeats,
            # backend.go:78-97)
            from seaweedfs_tpu.storage import backend as _bk

            _bk.ensure_builtin_factories()
            _bk.load_backend_config(storage_backends)
        self.store = Store(
            directories,
            max_volume_counts,
            ec_backend=self.ec_codec,
            needle_map_kind=needle_map_kind,
        )
        self.host = host
        self.port = port
        self.grpc_port = port + 10000
        # seed masters (comma-separated); self.master tracks the one we
        # currently talk to and follows leader hints from heartbeats
        # (volume_grpc_client_to_master.go:34-53)
        self.seed_masters = [m.strip() for m in master.split(",") if m.strip()] if master else []
        self.master = self.seed_masters[0] if self.seed_masters else master
        self._master_rr = 0
        self.public_url = public_url or f"{host}:{port}"
        # advertised INTERNAL address (heartbeat ip/port → the url every
        # peer, repair verb, and replica fan-out dials): differs from
        # the bind address when the cluster must reach this server
        # through a proxy or NAT hop — including a weedchaos ChaosProxy
        # pair (docs/CHAOS.md), which is how a live node gets
        # partitioned without root. Self-identity checks go through
        # _self_urls(), which matches BOTH the bind and the announced
        # address — replica fan-out, delete cascades, and shard
        # gathers must never dial this server through its own
        # announced hop.
        self.announce_host, self.announce_port = host, port
        if announce:
            a_host, _, a_port = announce.partition(":")
            self.announce_host, self.announce_port = a_host, int(a_port)
        self.data_center = data_center
        self.rack = rack
        self.heartbeat_interval = heartbeat_interval
        self.read_redirect = read_redirect
        self.guard = guard  # security.Guard; None = security off
        self.fix_jpg_orientation = fix_jpg_orientation
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        self._stop = threading.Event()
        self._force_full_heartbeat = threading.Event()
        # set by Store.notify_change on any inventory change: wakes the
        # heartbeat generator so the delta beat goes out NOW instead of
        # on the next tick. This is what makes the EC-migration
        # pipeline's mount-before-delete ordering visible to the master
        # in order (reference: the NewVolumes/NewEcShards channel pushes
        # in volume_grpc_client_to_master.go — mount/delete events
        # interleave the ticker there too).
        self._hb_wake = threading.Event()
        self.store.notify_change = self._hb_wake.set
        self._grpc_server: grpc.Server | None = None
        self._http_server: WeedHTTPServer | None = None
        self._hb_thread: threading.Thread | None = None
        self._metrics_push: threading.Thread | None = None
        self._metrics_cfg: tuple | None = None
        # vid -> (expires, [urls]); keeps the master off the per-write
        # hot path (the reference's wdclient vidMap role)
        self._location_cache: dict[int, tuple[float, list[str]]] = {}
        self._location_cache_ttl = 10.0
        # -workers mode (server/volume_workers.py): SO_REUSEPORT on the
        # public listener so read-worker processes can share the port,
        # plus a loopback internal listener the workers proxy through
        self.reuse_port = reuse_port
        self.internal_port = internal_port
        self._internal_server: WeedHTTPServer | None = None
        # -shardWrites: volume-ownership write sharding across the
        # -workers processes. Writer k of n_writers owns vids with
        # vid % n_writers == k (lead is writer 0) and is the ONLY
        # process that appends those volumes' .dat/.idx — the
        # single-writer-per-volume invariant the reference enforces
        # in-process (volume_read_write.go:66), partitioned across
        # processes. Ownership of a vid reverts permanently to the
        # lead (self._shard_taken) before any file-rewriting admin op
        # — vacuum, EC encode, readonly, delete — via _ensure_owned's
        # release handshake with the owning worker.
        # scrub plane (docs/SCRUB.md): background integrity sweeps over
        # every local volume, rate-limited so foreground p99 survives.
        # scrub_interval <= 0 disables the engine; quarantine reporting
        # (heartbeats, /status) still works — foreground reads keep
        # quarantining truncated shards either way.
        self.store.node_label = f"{host}:{port}"
        self.scrub: "object | None" = None
        if scrub_interval > 0:
            from seaweedfs_tpu.scrub import ScrubEngine

            self.scrub = ScrubEngine(
                self.store,
                interval=scrub_interval,
                rate_mb_s=scrub_rate_mb_s,
                fetcher_factory=self._remote_shard_fetcher,
                on_event=self._hb_wake.set,
                node_label=self.store.node_label,
            )
        # keep-alive housekeeping knobs for both serving loops
        # (`-serveIdleMs`/`-serveMaxReqs`, docs/SERVING.md); 0 = off
        self.serve_idle_ms = serve_idle_ms
        self.serve_max_reqs = serve_max_reqs
        # QoS plane (docs/QOS.md): group commit on the write path — a
        # configured committer routes POSTs through commit windows (and
        # per-POST fsync when -commitFsync rides alone); the C POST
        # fast path declines to Python while one is installed so every
        # write can join a window / get its durability flush
        self.group_commit = None
        if commit_window_us > 0 or commit_fsync:
            from seaweedfs_tpu.qos.group_commit import GroupCommitter

            self.group_commit = GroupCommitter(
                window_us=commit_window_us,
                max_bytes=commit_bytes,
                max_batch=commit_batch,
                fsync=commit_fsync,
            )
        # in-flight request tracking, shipped on heartbeats so the
        # master's pick-for-write can weigh nodes by live load
        self.load = qos.LoadTracker()
        # weedguard (docs/HEALTH.md): the local disk watchdog flips the
        # node into read-only lame-duck mode on repeated EIO/ENOSPC
        # (announced on the next forced beat; new writes shed with
        # 503), SIGTERM sets `draining` (graceful drain — see drain()),
        # and the hinted-handoff spool + agent keep replicated writes
        # available while one replica is down: a failed replica hop
        # durably spools the request here and replays it on heal.
        from seaweedfs_tpu.cluster.health import DiskWatchdog
        from seaweedfs_tpu.server.handoff import HandoffAgent, HintStore

        self.watchdog = DiskWatchdog()
        self.watchdog.on_trip = self._hb_wake.set
        self.draining = False
        self.hints = HintStore(os.path.join(directories[0], ".weed_handoff"))
        # replays re-sign with OUR key on signed clusters: the client
        # JWT spooled in a hint expires on token timescales while an
        # outage can last longer
        sign = None
        if guard is not None and guard.signing_key:
            sign = lambda fid: f"BEARER {guard.sign_write(fid)}"  # noqa: E731
        self.handoff = HandoffAgent(self.hints, sign=sign)
        # per-client admission control (token bucket + in-flight cap);
        # None = accept everything, today's behavior
        self.admission = None
        if admission_rate > 0 or admission_inflight > 0:
            from seaweedfs_tpu.qos.admission import AdmissionController

            self.admission = AdmissionController(
                rate=admission_rate,
                burst=admission_burst,
                max_inflight=admission_inflight,
                procs=admission_procs,
                label="volume",
                shm_path=admission_shm_path,
            )
        self.shard_writes = shard_writes
        self.n_writers = max(1, n_writers)
        self._shard_taken: set[int] = set()
        self._shard_lock = threading.Lock()  # guards the sets/dicts only
        # per-vid handshake locks: the release round-trip can block for
        # seconds on a wedged worker and must not serialize takeovers
        # (or hop-writes) of unrelated vids behind one global lock
        self._shard_vid_locks: dict[int, threading.Lock] = {}

    # ------------------------------------------------------------------
    # status UI (server/volume_server_ui/templates.go role)
    def _render_ui(self) -> str:
        import html as _html

        rows = []
        for loc in self.store.locations:
            for vid, v in sorted(loc.volumes.items()):
                rows.append(
                    f"<tr><td>{vid}</td><td>{_html.escape(v.collection)}</td>"
                    f"<td>{v.data_file_size()}</td><td>{v.file_count()}</td>"
                    f"<td>{v.deleted_count()}</td>"
                    f"<td>{'ro' if v.read_only else 'rw'}</td></tr>"
                )
            for vid, ev in sorted(loc.ec_volumes.items()):
                shards = ",".join(str(s) for s in ev.shard_ids())
                rows.append(
                    f"<tr><td>{vid}</td><td>{_html.escape(ev.collection)}</td>"
                    f"<td colspan=3>EC shards: {shards}</td><td>ec</td></tr>"
                )
        from seaweedfs_tpu.util.status_ui import status_page

        return status_page(
            "SeaweedFS-TPU Volume",
            f"Volume Server {self.host}:{self.port}",
            f"master: {_html.escape(self.master or '(none)')} &middot; "
            f"ec codec: {self.ec_codec or 'auto'}",
            ["Id", "Collection", "Size", "Files", "Deleted", "Mode"],
            "".join(rows),
            ["/status", "/metrics"],
        )

    # ------------------------------------------------------------------
    # heartbeat client (volume_grpc_client_to_master.go)
    # full beats every Nth cycle keep master state authoritative; the
    # cycles between send only volume-set changes so steady-state
    # chatter is O(changes), not O(volumes) (master.proto:43-44
    # new_volumes/deleted_volumes delta beats)
    _FULL_HEARTBEAT_EVERY = 10

    @staticmethod
    def _add_vol_stats(field, infos) -> None:
        for v in infos:
            field.add(
                id=v.id,
                size=v.size,
                collection=v.collection,
                file_count=v.file_count,
                delete_count=v.delete_count,
                deleted_byte_count=v.deleted_byte_count,
                read_only=v.read_only,
                replica_placement=v.replica_placement,
                version=v.version,
                ttl=v.ttl,
            )

    def _heartbeat_requests(self):
        last_vids: dict[int, object] | None = None  # None => send full
        last_full_infos: dict[int, object] = {}
        beat = 0
        while not self._stop.is_set():
            # clear BEFORE collecting: a change landing mid-collect
            # re-sets the event and triggers another immediate beat
            # rather than being absorbed into this one and lost
            self._hb_wake.clear()
            if self._force_full_heartbeat.is_set():
                # master asked for the full inventory (it lost our
                # state to a liveness sweep or a leader change)
                # weedlint: ignore[race-check-then-act] — Event consume: a set() landing between is_set and clear is absorbed into the full beat this branch is about to send, so no request is ever lost
                self._force_full_heartbeat.clear()
                last_vids = None
            if self.shard_writes:
                # worker-owned volumes: fold the owners' appended .idx
                # entries in so file counts ride the beat accurately
                for loc in self.store.locations:
                    for vid, v in list(loc.volumes.items()):
                        if self._shard_is_foreign(vid):
                            v.refresh_from_idx()
            hb = self.store.collect_heartbeat()
            req = master_pb2.HeartbeatRequest(
                ip=self.announce_host,
                port=self.announce_port,
                public_url=self.public_url,
                max_volume_count=sum(
                    loc.max_volume_count for loc in self.store.locations
                ),
                max_file_key=hb.max_file_key,
                data_center=self.data_center,
                rack=self.rack,
                has_no_ec_shards=not hb.ec_shards,
                # QoS plane: live load for queue-depth-aware assignment
                # (master pick_for_write power-of-two-choices)
                in_flight_requests=self.load.inflight(),
                write_queue_depth=(
                    self.group_commit.depth()
                    if self.group_commit is not None
                    else 0
                ),
                # health plane (docs/HEALTH.md): graceful-degradation
                # flags + cumulative error counters for the master's
                # per-node EWMAs
                lame_duck=self.watchdog.lame_duck,
                draining=self.draining,
                io_errors=self.watchdog.io_errors,
                request_errors=self.load.errors(),
            )
            # signature catches in-place changes (growth past the size
            # limit, read-only flips, delete counts) so they propagate
            # on the next delta beat, not only on the Nth full beat
            def sig(v):
                return (v.size, v.file_count, v.delete_count, v.read_only)

            current = {v.id: v for v in hb.volumes}
            full = last_vids is None or beat % self._FULL_HEARTBEAT_EVERY == 0
            if full:
                req.has_no_volumes = not hb.volumes
                self._add_vol_stats(req.volumes, hb.volumes)
            else:
                new = [
                    v
                    for vid, v in current.items()
                    if vid not in last_vids or last_vids[vid] != sig(v)
                ]
                gone = [
                    hb_v
                    for vid, hb_v in last_full_infos.items()
                    if vid not in current
                ]
                self._add_vol_stats(req.new_volumes, new)
                self._add_vol_stats(req.deleted_volumes, gone)
            last_vids = {vid: sig(v) for vid, v in current.items()}
            last_full_infos = current
            beat += 1
            for s in hb.ec_shards:
                req.ec_shards.add(
                    id=s.id, collection=s.collection, ec_index_bits=s.ec_index_bits
                )
            for row in self._collect_scrub_stats():
                req.scrub_stats.add(**row)
            yield req
            # next beat on the tick, on an inventory change, or on stop
            # — whichever comes first
            self._hb_wake.wait(self.heartbeat_interval)

    def _collect_scrub_stats(self) -> list[dict]:
        """ScrubStat heartbeat rows: the engine's health records merged
        with the store's quarantine registry (which also fills when the
        engine is off — foreground reads quarantine truncated shards
        too). Complete snapshot every beat; the master overwrites."""
        rows: dict[tuple[int, bool], dict] = {}
        if self.scrub is not None:
            for h in self.scrub.health_rows():
                rows[(h.volume_id, h.is_ec)] = {
                    "volume_id": h.volume_id,
                    "is_ec": h.is_ec,
                    "last_sweep_unix": int(h.last_sweep_unix),
                    "scanned_bytes": h.scanned_bytes,
                    # CURRENT damage, not history: a repaired volume's
                    # next clean sweep zeroes this, so the master's
                    # repair scheduler converges (cumulative totals
                    # stay in metrics and /scrub/status)
                    "corruptions_found": h.sweep_corruptions,
                    "quarantined_shard_bits": 0,
                    "last_error": h.last_error[:300],
                }
        for vid, per_vid in list(self.store.quarantined.items()):
            row = rows.setdefault(
                (vid, True),
                {
                    "volume_id": vid,
                    "is_ec": True,
                    "last_sweep_unix": 0,
                    "scanned_bytes": 0,
                    "corruptions_found": 0,
                    "quarantined_shard_bits": 0,
                    "last_error": "; ".join(
                        f"shard {sid}: {why}"
                        for sid, why in sorted(per_vid.items())
                    )[:300],
                },
            )
            row["quarantined_shard_bits"] = self.store.quarantined_shard_bits(
                vid
            )
        return list(rows.values())

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                with rpc.dial(self._master_grpc()) as ch:
                    stub = rpc.master_stub(ch)
                    for resp in stub.Heartbeat(self._heartbeat_requests()):
                        if resp.volume_size_limit:
                            self.volume_size_limit = resp.volume_size_limit
                        if resp.request_full_heartbeat:
                            self._force_full_heartbeat.set()
                        if resp.metrics_address:
                            # master ships the pushgateway config in the
                            # heartbeat response (master_grpc_server.go:80);
                            # a NEW address/interval (e.g. from a new
                            # leader) replaces the running loop
                            cfg = (
                                resp.metrics_address,
                                resp.metrics_interval_seconds or 15,
                            )
                            if cfg != self._metrics_cfg:
                                from seaweedfs_tpu.stats.metrics import (
                                    start_push_loop,
                                )

                                if self._metrics_push is not None:
                                    self._metrics_push.stop_event.set()
                                # weedlint: ignore[race-check-then-act] — the heartbeat thread is the sole writer of _metrics_cfg/_metrics_push; other threads only read the push handle
                                self._metrics_cfg = cfg
                                # weedlint: ignore[race-check-then-act] — single-writer (heartbeat thread) swap, see _metrics_cfg above
                                self._metrics_push = start_push_loop(
                                    f"http://{cfg[0]}",
                                    job=f"volume_{self.host}_{self.port}",
                                    interval_sec=cfg[1],
                                    stop_event=threading.Event(),
                                )
                        if resp.leader and resp.leader != self.master:
                            # follow the leader hint: reconnect there
                            # weedlint: ignore[race-check-then-act] — master is re-resolved only by the heartbeat thread (leader hint here, seed rotation below); readers tolerate one stale beat
                            self.master = resp.leader
                            break
                        if self._stop.is_set():
                            return
                    else:
                        # stream ended cleanly (e.g. a leaderless
                        # follower redirecting to itself): back off so
                        # election windows don't become a reconnect storm
                        self._stop.wait(0.2)
            except grpc.RpcError:
                # rotate through the seed masters until one answers
                if len(self.seed_masters) > 1:
                    self._master_rr = (self._master_rr + 1) % len(self.seed_masters)
                    # weedlint: ignore[race-check-then-act] — single-writer seed rotation on the heartbeat thread, same contract as the leader-hint site above
                    self.master = self.seed_masters[self._master_rr]
                self._stop.wait(0.2 if len(self.seed_masters) > 1 else 1.0)

    def _master_grpc(self) -> str:
        host, _, port = self.master.partition(":")
        return f"{host}:{int(port) + 10000}"

    def _lookup_locations(self, vid: int) -> list[str] | None:
        """Replica urls for a vid via the master, cached briefly."""
        cached = self._location_cache.get(vid)
        now = time.time()
        if cached and cached[0] > now:
            return cached[1]
        try:
            with rpc.dial(self._master_grpc()) as ch:
                resp = rpc.master_stub(ch).LookupVolume(
                    master_pb2.LookupVolumeRequest(vids=[str(vid)]), timeout=5
                )
        except grpc.RpcError:
            return cached[1] if cached else None
        urls = [
            l.url for entry in resp.vid_locations for l in entry.locations
        ]
        self._location_cache[vid] = (now + self._location_cache_ttl, urls)
        return urls

    # ------------------------------------------------------------------
    # gRPC admin servicer
    def AllocateVolume(self, req: pb.AllocateVolumeRequest, context):
        self.store.add_volume(
            req.volume_id, req.collection, req.replication or "000", req.ttl
        )
        return pb.AllocateVolumeResponse()

    def VolumeDelete(self, req: pb.VolumeDeleteRequest, context):
        self._ensure_owned(req.volume_id)
        self.store.delete_volume(req.volume_id)
        return pb.VolumeDeleteResponse()

    def VolumeMount(self, req, context):
        self._ensure_owned(req.volume_id)
        if not self.store.mount_volume(req.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        return pb.VolumeMountResponse()

    def VolumeUnmount(self, req, context):
        self._ensure_owned(req.volume_id)
        if not self.store.unmount_volume(req.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        return pb.VolumeUnmountResponse()

    def VolumeMarkReadonly(self, req, context):
        self._ensure_owned(req.volume_id)
        self.store.mark_volume_readonly(req.volume_id)
        return pb.VolumeMarkReadonlyResponse()

    def VolumeMarkWritable(self, req, context):
        self.store.mark_volume_writable(req.volume_id)
        return pb.VolumeMarkWritableResponse()

    def DeleteCollection(self, req: pb.DeleteCollectionRequest, context):
        for loc in self.store.locations:
            doomed = [
                vid
                for vid, vol in loc.volumes.items()
                if vol.collection == req.collection
            ]
            for vid in doomed:
                loc.delete_volume(vid)
        return pb.DeleteCollectionResponse()

    def VolumeSyncStatus(self, req, context):
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        return pb.VolumeSyncStatusResponse(
            volume_id=v.id,
            collection=v.collection,
            replication=str(v.super_block.replica_placement),
            ttl=str(v.ttl),
            tail_offset=v.data_file_size(),
            compact_revision=v.super_block.compaction_revision,
            idx_file_size=v.nm.index_file_size(),
        )

    def BatchDelete(self, req: pb.BatchDeleteRequest, context):
        out = pb.BatchDeleteResponse()
        for fid_str in req.file_ids:
            result = out.results.add(file_id=fid_str)
            try:
                fid = FileId.parse(fid_str)
                n = Needle(cookie=fid.cookie, id=fid.key)
                size = self.store.delete_needle(fid.volume_id, n)
                result.status = 202
                result.size = size
            except Exception as e:  # noqa: BLE001
                result.status = 500
                result.error = str(e)
        return out

    # vacuum 4-phase (volume_grpc_vacuum.go)
    def VacuumVolumeCheck(self, req, context):
        # read-only phase: an accurate garbage ratio needs the owner's
        # appended entries folded in, NOT a permanent ownership seizure
        # (the master's periodic sweep checks every volume — takeover
        # here would collapse -shardWrites to lead-only in one sweep)
        v0 = self.store.find_volume(req.volume_id)
        if v0 is not None and self._shard_is_foreign(req.volume_id):
            v0.refresh_from_idx()
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        return pb.VacuumVolumeCheckResponse(garbage_ratio=v.garbage_level())

    def VacuumVolumeCompact(self, req, context):
        self._ensure_owned(req.volume_id)
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        v.compact()
        return pb.VacuumVolumeCompactResponse()

    def VacuumVolumeCommit(self, req, context):
        self._ensure_owned(req.volume_id)
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        v.commit_compact()
        return pb.VacuumVolumeCommitResponse()

    def VacuumVolumeCleanup(self, req, context):
        self._ensure_owned(req.volume_id)
        v = self.store.find_volume(req.volume_id)
        if v is not None:
            v.cleanup_compact()
        return pb.VacuumVolumeCleanupResponse()

    # copy/tail (volume_grpc_copy.go, volume_grpc_tail.go)
    def VolumeCopy(self, req: pb.VolumeCopyRequest, context):
        """Replicate a whole volume from another node by pulling its
        .dat/.idx over the CopyFile stream (volume_grpc_copy.go:25)."""
        self._ensure_owned(req.volume_id)
        if self.store.has_volume(req.volume_id):
            context.abort(
                grpc.StatusCode.ALREADY_EXISTS,
                f"volume {req.volume_id} already exists",
            )
        loc = self.store.locations[0]
        base = volume_base_name(loc.directory, req.collection, req.volume_id)
        host, _, port = req.source_data_node.partition(":")
        with rpc.dial(f"{host}:{int(port) + 10000}") as ch:
            stub = rpc.volume_stub(ch)
            for ext in (".dat", ".idx"):
                with open(base + ext, "wb") as f:
                    for resp in stub.CopyFile(
                        pb.CopyFileRequest(
                            volume_id=req.volume_id,
                            collection=req.collection,
                            ext=ext,
                        )
                    ):
                        f.write(resp.file_content)
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(loc.directory, req.volume_id, req.collection, create=False)
        loc.volumes[req.volume_id] = v
        return pb.VolumeCopyResponse(last_append_at_ns=v.last_append_at_ns)

    def CopyFile(self, req: pb.CopyFileRequest, context):
        base = self._base_name(req.collection, req.volume_id)
        path = base + req.ext
        if not os.path.exists(path):
            context.abort(grpc.StatusCode.NOT_FOUND, f"no file {path}")
        stop = req.stop_offset or os.path.getsize(path)
        with open(path, "rb") as f:
            sent = 0
            while sent < stop:
                chunk = f.read(min(COPY_CHUNK, stop - sent))
                if not chunk:
                    break
                sent += len(chunk)
                yield pb.CopyFileResponse(file_content=chunk)

    def VolumeIncrementalCopy(self, req, context):
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        # stream the .dat tail whose records are newer than since_ns
        # (binary search over AppendAtNs, volume_backup.go:170); linear
        # scan from the superblock is equivalent on the append-only file
        for blob, _n, _end in self._iter_needles_since(v, req.since_ns):
            yield pb.VolumeIncrementalCopyResponse(file_content=blob)

    # tail follow/replicate (volume_grpc_tail.go)
    def _iter_needles_since(self, v, since_ns: int, start_offset: int = 0):
        """(blob, needle) for needles appended after since_ns, in .dat
        order, starting the scan at start_offset (sendNeedlesSince
        role; linear scan is equivalent to the binary search on the
        append-only file). The generator's .end_offset attribute is
        unusable from a generator, so callers that poll should pass the
        last end offset back in — see VolumeTailSender."""
        from seaweedfs_tpu.storage.needle import get_actual_size
        from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

        offset = max(start_offset, SUPER_BLOCK_SIZE + len(v.super_block.extra))
        size = v.data_file_size()
        while offset < size:
            header = v._read_at(offset, 16)
            if len(header) < 16:
                return
            _, _, nsize = Needle.parse_header(header + bytes(16))
            record = get_actual_size(
                nsize if nsize != 0xFFFFFFFF else 0, v.version
            )
            blob = v._read_at(offset, record)
            try:
                n = Needle.from_bytes(blob, v.version)
            except ValueError:
                return
            if n.append_at_ns > since_ns:
                yield blob, n, offset + record
            offset += record

    def VolumeTailSender(self, req, context):
        """Stream needles appended since since_ns as (header, body)
        pairs; keep following until idle for idle_timeout_seconds
        (0 = follow forever) (volume_grpc_tail.go:16-54)."""
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found"
            )
        last_ns = req.since_ns
        draining = req.idle_timeout_seconds
        # resume each poll from the previous end-of-file position: the
        # .dat is append-only, so a follow-forever tail must not rescan
        # the whole volume every 2 seconds
        resume_at = 0
        while not self._stop.is_set():
            progressed = False
            for blob, n, end in self._iter_needles_since(v, last_ns, resume_at):
                yield pb.VolumeTailSenderResponse(
                    needle_header=blob[:16],
                    needle_body=blob[16:],
                    is_last_chunk=False,
                )
                last_ns = max(last_ns, n.append_at_ns)
                resume_at = end
                progressed = True
            if req.idle_timeout_seconds == 0:
                self._stop.wait(2.0)
                continue
            if progressed:
                draining = req.idle_timeout_seconds
            else:
                draining -= 1
                if draining <= 0:
                    return
            self._stop.wait(1.0)

    def VolumeTailReceiver(self, req, context):
        """Pull a source server's tail into the local volume
        (volume_grpc_tail.go:79 VolumeTailReceiver)."""
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found"
            )
        host, _, port = req.source_volume_server.partition(":")
        with rpc.dial(f"{host}:{int(port) + 10000}") as ch:
            for resp in rpc.volume_stub(ch).VolumeTailSender(
                pb.VolumeTailSenderRequest(
                    volume_id=req.volume_id,
                    since_ns=req.since_ns,
                    idle_timeout_seconds=req.idle_timeout_seconds or 2,
                )
            ):
                blob = resp.needle_header + resp.needle_body
                try:
                    n = Needle.from_bytes(blob, v.version)
                except ValueError:
                    continue
                if len(n.data) == 0:
                    # zero-size record = tombstone (the reference keys
                    # replicated deletes off n.Size == 0 the same way)
                    v.delete_needle(n)
                else:
                    v.write_needle(n)
        return pb.VolumeTailReceiverResponse()

    # EC verbs (volume_grpc_erasure_coding.go)
    def _base_name(self, collection: str, vid: int) -> str:
        v = self.store.find_volume(vid)
        if v is not None:
            return v.base_name
        for loc in self.store.locations:
            base = volume_base_name(loc.directory, collection, vid)
            if any(
                os.path.exists(base + ext)
                for ext in (".dat", ".ecx", ".ec00", ".idx")
            ):
                return base
        return volume_base_name(self.store.locations[0].directory, collection, vid)

    def _new_rs(self):
        from seaweedfs_tpu.ec.codec import new_encoder

        return new_encoder(backend=self.ec_codec)

    def VolumeEcShardsGenerate(self, req, context):
        self._ensure_owned(req.volume_id)
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found")
        base = v.base_name
        # durable ordering (weedcrash ec-encode workload): shard bytes
        # fsynced BEFORE the .ecx publish — a crash can then never leave
        # a complete-looking index over page-cache-only shard files.
        # want_crcs: the pipelined drivers fold per-shard CRC-32C out of
        # the codec pass for free — logged so an operator can cross-check
        # a suspect shard file against the encode-time checksum without
        # re-reading the survivors
        st: dict = {}
        ec_files.write_ec_files(
            base, rs=self._new_rs(), durable=True, stats=st, want_crcs=True
        )
        crcs = st.get("shard_crcs")
        if crcs:
            wlog.info(
                "ec.generate vid=%s shard_crc32c=%s",
                req.volume_id,
                ",".join(f"{c:08x}" for c in crcs),
            )
            self._publish_ecc(base, crcs)
        ec_files.write_sorted_file_from_idx(base, durable=True)
        return pb.VolumeEcShardsGenerateResponse()

    def VolumeEcShardsBatchGenerate(self, req, context):
        """N local sealed volumes → shard files through ONE mesh
        program per tile round (ec_files.write_ec_files_batch over
        parallel/mesh_codec.py). The driver self-provisions the mesh
        ('vol' axis = gcd of batch and device count, so any batch —
        and any WEED_EC_PIPELINE_BATCH chunk of it — shards cleanly)
        and, with durable=True, fsyncs every shard file before
        returning on both arms, so the .ecx publish below can imply
        shard bytes are on disk (the single-volume verb's weedcrash
        ordering)."""
        bases = []
        for vid in req.volume_ids:
            v = self.store.find_volume(vid)
            if v is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND, f"volume {vid} not found"
                )
            bases.append(v.base_name)
        if bases:
            st: dict = {}
            ec_files.write_ec_files_batch(
                bases, durable=True, stats=st, want_crcs=True
            )
            for vid, base, crcs in zip(
                req.volume_ids, bases, st.get("shard_crcs") or []
            ):
                wlog.info(
                    "ec.batch_generate vid=%s shard_crc32c=%s",
                    vid,
                    ",".join(f"{c:08x}" for c in crcs),
                )
                self._publish_ecc(base, crcs)
            for base in bases:
                ec_files.write_sorted_file_from_idx(base, durable=True)
        return pb.VolumeEcShardsBatchGenerateResponse()

    def VolumeEcShardsRebuild(self, req, context):
        """Regenerate missing shard files. With every survivor local
        this is the classic local-file rebuild; when survivors are
        missing locally but mounted elsewhere (the rack-gather case —
        ec.rebuild no longer pre-copies them), the pipelined
        ec_stream driver reads those shards straight off their holders
        tile by tile, overlapping the remote fetch with reconstruction
        instead of serializing a full cluster copy before decoding
        byte one."""
        with trace.span(
            "volume.ec_rebuild",
            header=trace.header_from_grpc_context(context),
            node=f"{self.host}:{self.port}",
        ) as sp:
            if sp:
                sp.annotate("vid", req.volume_id)
            return self._ec_shards_rebuild(req, context)

    def _ec_shards_rebuild(self, req, context):
        base = self._base_name(req.collection, req.volume_id)
        present, missing = ec_files.shard_presence(base)
        if not missing or not self.master:
            st: dict = {}
            rebuilt = ec_files.rebuild_ec_files(
                base, rs=self._new_rs(), durable=True, stats=st,
                want_crcs=True,
            )
            self._log_rebuild_crcs(req.volume_id, base, st)
            return pb.VolumeEcShardsRebuildResponse(rebuilt_shard_ids=rebuilt)
        # with a master, always learn which "missing" shards are in
        # fact mounted elsewhere: they serve as remote survivors and
        # are EXCLUDED from the rebuild targets — even a rebuilder
        # holding >= 10 local shards must not regenerate (and later
        # double-mount) shards the cluster still has
        readers, close_readers = self._remote_rebuild_readers(
            req.volume_id, {i for i, p in enumerate(present) if p}
        )
        try:
            if not readers:
                st = {}
                rebuilt = ec_files.rebuild_ec_files(
                    base, rs=self._new_rs(), durable=True, stats=st,
                    want_crcs=True,
                )
                self._log_rebuild_crcs(req.volume_id, base, st)
            else:
                from seaweedfs_tpu.ec import ec_stream, repair_session

                rs = self._new_rs()
                rebuild_fn = fetch_fn = None
                if not ec_files._use_stream_driver(rs):
                    rebuild_fn, fetch_fn = ec_stream.local_rebuild_fns(
                        rs, want_crcs=True
                    )
                # repair piggyback (docs/SCRUB.md): degraded GETs of
                # this volume donate the tiles they decode while the
                # session is open, and tiles already decoded for past
                # degraded reads seed it — the driver then gathers
                # survivors only for the gaps
                targets = [i for i in missing if i not in readers]
                sess = repair_session.open_session(req.volume_id, targets)
                try:
                    # inside the try: a raise here must still unregister
                    # the session, or every later degraded read donates
                    # into a dead one (bounded by the cap, held forever)
                    ev = self.store.find_ec_volume(req.volume_id)
                    if ev is not None:
                        ev.donate_cached_tiles(sess)
                    st = {}
                    rebuilt = ec_stream.stream_rebuild_ec_files(
                        base,
                        rebuild_fn=rebuild_fn,
                        fetch_fn=fetch_fn,
                        remote_readers=readers,
                        session=sess,
                        durable=True,
                        stats=st,
                        want_crcs=True,
                    )
                    self._log_rebuild_crcs(req.volume_id, base, st)
                except ValueError as e:
                    context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
                finally:
                    repair_session.close_session(sess)
        finally:
            close_readers()
        return pb.VolumeEcShardsRebuildResponse(rebuilt_shard_ids=rebuilt)

    def VolumeEcShardsBatchRebuild(self, req, context):
        """Rebuild N volumes' missing shards, batched: volumes whose
        survivors are ALL local and whose missing shards are missing
        cluster-wide ride one sharded mesh decode program per tile
        round (ec_files.rebuild_ec_files_batch, grouped there by
        damage signature) — the RepairScheduler's answer to a node
        loss surfacing many small volumes with identical damage at
        once. Volumes that DON'T fit that shape (a "missing" shard is
        mounted elsewhere — regenerating it here would double-mount —
        or survivors must be rack-gathered) fall through to the
        single-volume rebuild path per volume, so the verb is safe to
        aim at any mix. Reuses the BatchGenerate message pair: ids in,
        empty response (rebuilt ids are logged; callers recompute
        presence, as ec.rebuild already does)."""
        with trace.span(
            "volume.ec_rebuild_batch",
            header=trace.header_from_grpc_context(context),
            node=f"{self.host}:{self.port}",
        ) as sp:
            if sp:
                sp.annotate("vids", list(req.volume_ids))
            batch: list[tuple[int, str]] = []
            for vid in req.volume_ids:
                ev = self.store.find_ec_volume(vid)
                base = (
                    ev.base_name
                    if ev is not None
                    else self._base_name("", vid)
                )
                present, missing = ec_files.shard_presence(base)
                if not missing:
                    continue
                remote = self._cluster_present_shards(vid)
                if (
                    sum(present) >= ec_files.DATA_SHARDS
                    and not (set(missing) & remote)
                ):
                    batch.append((vid, base))
                else:
                    self._ec_shards_rebuild(
                        pb.VolumeEcShardsRebuildRequest(volume_id=vid),
                        context,
                    )
            if batch:
                st: dict = {}
                try:
                    ec_files.rebuild_ec_files_batch(
                        [base for _, base in batch],
                        durable=True,
                        stats=st,
                        want_crcs=True,
                    )
                except ValueError as e:
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION, str(e)
                    )
                for (vid, base), crcs in zip(
                    batch, st.get("shard_crcs") or []
                ):
                    self._log_rebuild_crcs(vid, base, {"shard_crcs": crcs})
        return pb.VolumeEcShardsBatchGenerateResponse()

    def _cluster_present_shards(self, vid: int) -> set[int]:
        """Shard ids of `vid` mounted on OTHER nodes per the master —
        shards the batch-rebuild arm must not regenerate locally (the
        single verb's _remote_rebuild_readers exclusion, presence-only).
        Empty on no master / lookup failure — then every locally
        missing shard is a target, exactly what the single verb does on
        the same no-master / failed-lookup arms."""
        if not self.master:
            return set()
        try:
            with rpc.dial(self._master_grpc()) as ch:
                resp = rpc.master_stub(ch).LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid),
                    timeout=5,
                )
        except grpc.RpcError:
            return set()
        me = self._self_urls()
        return {
            e.shard_id
            for e in resp.shard_id_locations
            if any(l.url not in me for l in e.locations)
        }

    @staticmethod
    def _publish_ecc(base: str, crcs) -> None:
        """Publish/refresh the `.ecc` scrub sidecar (ec/ecc_sidecar.py)
        from encode/rebuild-pass CRCs. Callers reach here only on the
        durable=True arms, so the shard bytes the sidecar attests are
        already fsynced — the ordering the weedcrash ecc_publish
        workload enforces. Best-effort: a sidecar we fail to write
        just means the scrubber takes the (loud) parity path."""
        from seaweedfs_tpu.ec import ecc_sidecar

        if not ecc_sidecar.ecc_enabled():
            return
        try:
            ecc_sidecar.write_sidecar(
                base, crcs, total_shards=ec_files.TOTAL_SHARDS
            )
        except OSError as e:
            wlog.warning("ec: .ecc sidecar publish failed for %s: %r", base, e)

    def _log_rebuild_crcs(self, vid: int, base: str, st: dict) -> None:
        """Operator breadcrumb: encode-pass CRC-32C of every rebuilt
        shard file (fused out of the codec pass — see the generate
        verb), keyed so a later scrub mismatch can be triaged against
        what the rebuild actually produced. Also merges the fresh CRCs
        into the volume's `.ecc` sidecar: rebuilt shards are
        byte-identical to the originals, so the merge re-attests them
        and un-stales the sidecar's mtime in one publish."""
        crcs = st.get("shard_crcs")
        if crcs:
            wlog.info(
                "ec.rebuild vid=%s rebuilt_crc32c=%s",
                vid,
                ",".join(f"{i}:{c:08x}" for i, c in sorted(crcs.items())),
            )
            self._publish_ecc(base, dict(crcs))

    def _remote_rebuild_readers(self, vid: int, skip: set[int]):
        """(readers, closer): shard id → fetch(offset, size) callables
        over VolumeEcShardRead against holders learned from the master,
        for survivors not in `skip` (the locally-present set). One
        cached channel per holder — the stream driver's reader pool
        calls these concurrently, and grpc channels are thread-safe."""
        if not self.master:
            return {}, (lambda: None)
        try:
            with rpc.dial(self._master_grpc()) as ch:
                resp = rpc.master_stub(ch).LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid),
                    timeout=5,
                )
        except grpc.RpcError:
            return {}, (lambda: None)
        me = self._self_urls()
        locations: dict[int, list[str]] = {}
        for entry in resp.shard_id_locations:
            urls = [l.url for l in entry.locations if l.url not in me]
            if urls and entry.shard_id not in skip:
                locations[entry.shard_id] = urls
        channels: dict[str, grpc.Channel] = {}
        channels_lock = threading.Lock()

        def channel(url: str) -> grpc.Channel:
            with channels_lock:
                ch = channels.get(url)
                if ch is None:
                    host, _, port = url.partition(":")
                    ch = channels[url] = rpc.dial(f"{host}:{int(port) + 10000}")
                return ch

        # capture the trace context NOW: the stream driver's reader pool
        # calls these from its own threads, where the contextvar span is
        # not ambient — the captured metadata keeps remote-read spans
        # parented under the rebuild span that built the readers
        md = trace.grpc_metadata()
        # ...and the ambient deadline the same way (docs/CHAOS.md): the
        # rebuild verb runs under the caller's budget (the repair
        # scheduler stamps one), and the pool threads' per-read
        # timeouts shrink to what remains of it — a partitioned
        # survivor then fails the gather within the budget instead of
        # parking each read for the full per-op timeout
        factory_dl = _op_deadline.current()

        def make_reader(sid: int, urls: list[str]):
            def read(offset: int, size: int) -> bytes:
                # rebuild traffic pays the bandwidth arbiter before
                # pulling remote bytes — max-min share against
                # replication/handoff/tier, yielding to foreground
                # serving (docs/TIERING.md)
                get_arbiter().take("rebuild", size, stop=self._stop)
                last: Exception | None = None
                t_o = 30 if factory_dl is None else factory_dl.cap(30)
                # the hop HEADER rides too (re-stamped per read, the
                # remaining budget only shrinks): the shard holder can
                # then 504-fast-reject work this gather already gave up
                # on instead of serving bytes nobody will read
                call_md = md
                if factory_dl is not None:
                    call_md = tuple(md or ()) + (
                        (_op_deadline.DEADLINE_HEADER,
                         factory_dl.header_value()),
                    )
                for url in urls:
                    try:
                        data = b"".join(
                            r.data
                            for r in rpc.volume_stub(channel(url)).VolumeEcShardRead(
                                pb.VolumeEcShardReadRequest(
                                    volume_id=vid,
                                    shard_id=sid,
                                    offset=offset,
                                    size=size,
                                ),
                                timeout=t_o,
                                metadata=call_md,
                            )
                        )
                    except grpc.RpcError as e:
                        last = e
                        continue
                    if len(data) == size:
                        return data
                    last = ValueError(
                        f"shard {sid}@{url} returned {len(data)} of {size} "
                        f"bytes at {offset}"
                    )
                raise last or ValueError(f"no holder for ec shard {sid}")

            return read

        def closer() -> None:
            for ch in channels.values():
                ch.close()

        return (
            {sid: make_reader(sid, urls) for sid, urls in locations.items()},
            closer,
        )

    def VolumeEcShardsCopy(self, req: pb.VolumeEcShardsCopyRequest, context):
        """Pull shard files from the source node via its CopyFile stream."""
        target_dir = self.store.locations[0].directory
        base = volume_base_name(target_dir, req.collection, req.volume_id)
        host, _, port = req.source_data_node.partition(":")
        with rpc.dial(f"{host}:{int(port) + 10000}") as ch:
            stub = rpc.volume_stub(ch)
            exts = [ec_files.to_ext(sid) for sid in req.shard_ids]
            if req.copy_ecx_file:
                exts += [".ecx", ".ecj"]
            for ext in exts:
                try:
                    with open(base + ext, "wb") as f:
                        for resp in stub.CopyFile(
                            pb.CopyFileRequest(
                                volume_id=req.volume_id,
                                collection=req.collection,
                                ext=ext,
                                is_ec_volume=True,
                            )
                        ):
                            f.write(resp.file_content)
                except grpc.RpcError:
                    os.remove(base + ext)
                    if ext != ".ecj":  # .ecj is optional
                        raise
        return pb.VolumeEcShardsCopyResponse()

    def VolumeEcShardsDelete(self, req, context):
        base = self._base_name(req.collection, req.volume_id)
        for sid in req.shard_ids:
            p = base + ec_files.to_ext(sid)
            if os.path.exists(p):
                os.remove(p)
        # when no shards remain, drop the index files too
        if not any(
            os.path.exists(base + ec_files.to_ext(i)) for i in range(14)
        ):
            for ext in (".ecx", ".ecj"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
        return pb.VolumeEcShardsDeleteResponse()

    def VolumeEcShardsMount(self, req, context):
        self.store.mount_ec_shards(req.volume_id, req.collection, list(req.shard_ids))
        return pb.VolumeEcShardsMountResponse()

    def VolumeEcShardsUnmount(self, req, context):
        self.store.unmount_ec_shards(req.volume_id, list(req.shard_ids))
        return pb.VolumeEcShardsUnmountResponse()

    def VolumeEcShardRead(self, req: pb.VolumeEcShardReadRequest, context):
        # tracing: the trace context rides gRPC invocation metadata so a
        # remote shard read parents under the requesting hop's span and
        # keeps its plane tag (a scrub/repair-driven read stays visibly
        # scrub/repair traffic on THIS node's ring too)
        with trace.span(
            "volume.ec_shard_read",
            header=trace.header_from_grpc_context(context),
            nbytes=req.size,
            node=f"{self.host}:{self.port}",
        ) as sp:
            ev = self.store.find_ec_volume(req.volume_id)
            if ev is None:
                context.abort(grpc.StatusCode.NOT_FOUND, f"ec volume {req.volume_id} not found")
            shard = ev.shards.get(req.shard_id)
            remote = ev.remote
            if shard is None and not (
                remote is not None and req.shard_id in remote.shards
            ):
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"ec shard {req.volume_id}.{req.shard_id} not mounted",
                )
            if sp:
                sp.annotate("vid", req.volume_id)
                sp.annotate("shard", req.shard_id)
            if req.file_key:
                # tombstone check against .ecj-backed index state
                try:
                    ev.locate_needle(req.file_key)
                except NeedleNotFound:
                    yield pb.VolumeEcShardReadResponse(is_deleted=True)
                    return
            if shard is None:
                # tiered-away shard: peers keep fetching through this
                # node (the shard map still routes here — the heartbeat
                # advertises serving_shard_ids), and this node streams
                # the sub-range from its attached backend
                info = remote.shards[req.shard_id]
                size = int(info.get("size", remote.shard_size))
                remaining = min(req.size, max(0, size - req.offset))
                offset = req.offset
                while remaining > 0:
                    chunk = ev._remote_fetch(
                        req.shard_id, offset, min(COPY_CHUNK, remaining)
                    )
                    if not chunk:
                        context.abort(
                            grpc.StatusCode.UNAVAILABLE,
                            f"tier backend read failed for ec shard "
                            f"{req.volume_id}.{req.shard_id}",
                        )
                    yield pb.VolumeEcShardReadResponse(data=chunk)
                    offset += len(chunk)
                    remaining -= len(chunk)
                return
            # clamp the span to the shard: read_at treats past-EOF reads as
            # truncation (it guards the DEGRADED path, where short data must
            # never silently substitute), but a plain span read walking the
            # shard end — ec.verify's tile probe — just gets what exists
            remaining = min(req.size, max(0, shard.size - req.offset))
            offset = req.offset
            while remaining > 0:
                chunk = shard.read_at(offset, min(COPY_CHUNK, remaining))
                if not chunk:
                    break  # never spin yielding empties
                yield pb.VolumeEcShardReadResponse(data=chunk)
                offset += len(chunk)
                remaining -= len(chunk)

    def VolumeEcBlobDelete(self, req, context):
        ev = self.store.find_ec_volume(req.volume_id)
        if ev is not None:
            ev.delete_needle(req.file_key)
        return pb.VolumeEcBlobDeleteResponse()

    def VolumeEcShardsToVolume(self, req, context):
        """Decode mounted shards back into a normal volume
        (volume_grpc_erasure_coding.go:329)."""
        self._ensure_owned(req.volume_id)
        ev = self.store.find_ec_volume(req.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"ec volume {req.volume_id} not found")
        base = ev.base_name
        # ensure all shards present locally
        missing = [i for i in range(14) if i not in ev.shards]
        if missing:
            ec_files.rebuild_ec_files(base, rs=self._new_rs())
        ec_files.write_idx_file_from_ec_index(base)
        dat_size = ec_files.find_dat_file_size(base, ev.version)
        with open(base + ".dat", "wb") as out:
            written = 0
            while written < dat_size:
                chunk = min(4 * 1024 * 1024, dat_size - written)
                out.write(
                    ec_files.read_shard_intervals(base, written, chunk, dat_size)
                )
                written += chunk
        self.store.unmount_ec_shards(req.volume_id, list(range(14)))
        loc = self.store.locations[0]
        from seaweedfs_tpu.storage.volume import Volume

        loc.volumes[req.volume_id] = Volume(
            os.path.dirname(base) or ".", req.volume_id, req.collection, create=False
        )
        return pb.VolumeEcShardsToVolumeResponse()

    # ------------------------------------------------------------------
    # experimental select-from-files (volume_grpc_query.go:12)
    def Query(self, req, context):
        """Scan JSON-lines needles, filter + project, stream records
        (one JSON array of projections per passing line)."""
        from seaweedfs_tpu.query import Query as JsonQuery, query_json

        flt = JsonQuery(
            field=req.filter.field,
            op=req.filter.operand,
            value=req.filter.value,
        )
        for fid_str in req.from_file_ids:
            try:
                fid = FileId.parse(fid_str)
            except ValueError:
                continue
            v = self.store.find_volume(fid.volume_id)
            if v is None:
                continue
            try:
                n = v.read_needle(fid.key, cookie=fid.cookie)
            except (NeedleNotFound, CookieMismatch):
                continue
            out = []
            for line in bytes(n.data).decode("utf-8", "replace").splitlines():
                if not line.strip():
                    continue
                passed, values = query_json(line, list(req.selections), flt)
                if passed:
                    out.append(json.dumps(values))
            if out:
                yield pb.QueriedStripe(records=("\n".join(out) + "\n").encode())

    # ------------------------------------------------------------------
    # tiered storage (volume_grpc_tier_upload.go:14 / tier_download.go)
    def VolumeTierMoveDatToRemote(self, req, context):
        """Copy a sealed volume's .dat to a remote backend, streaming
        progress; the volume then serves reads via ranged GETs."""
        self._ensure_owned(req.volume_id)
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found"
            )
        if v.collection != req.collection:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"existing collection {v.collection!r} != {req.collection!r}",
            )
        updates: list = []

        def progress(done: int, pct: float) -> None:
            updates.append((done, pct))

        try:
            v.tier_upload(
                req.destination_backend_name,
                keep_local=req.keep_local_dat_file,
                progress=progress,
            )
        except (RuntimeError, OSError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        for done, pct in updates:
            yield pb.VolumeTierMoveDatToRemoteResponse(
                processed=done, processed_percentage=pct
            )

    def VolumeTierMoveDatFromRemote(self, req, context):
        """Bring a tiered volume's .dat back to local disk."""
        v = self.store.find_volume(req.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {req.volume_id} not found"
            )
        if v.collection != req.collection:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"existing collection {v.collection!r} != {req.collection!r}",
            )
        updates: list = []

        def progress(done: int, pct: float) -> None:
            updates.append((done, pct))

        try:
            v.tier_download(
                keep_remote=req.keep_remote_dat_file, progress=progress
            )
        except (RuntimeError, OSError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        for done, pct in updates:
            yield pb.VolumeTierMoveDatFromRemoteResponse(
                processed=done, processed_percentage=pct
            )

    # ------------------------------------------------------------------
    # remote shard fetch for degraded reads (store_ec.go:197-316)
    # shard-location cache tiers (store_ec.go:218-259): unhealthy
    # volumes (< k shards known) re-poll fast; healthy ones slowly
    _EC_LOC_TTL_UNHEALTHY = 11.0
    _EC_LOC_TTL_DEGRADED = 7 * 60.0
    _EC_LOC_TTL_FULL = 37 * 60.0

    def _cached_lookup_ec_locations(self, ev) -> None:
        """Refresh ev.shard_locations from the master when stale
        (cachedLookupEcShardLocations, store_ec.go:218-259)."""
        now = time.time()
        with ev.shard_locations_lock:
            count = len(ev.shard_locations)
            age = now - ev.shard_locations_refresh_time
            if count >= 14:
                ttl = self._EC_LOC_TTL_FULL
            elif count >= 10:
                ttl = self._EC_LOC_TTL_DEGRADED
            else:
                ttl = self._EC_LOC_TTL_UNHEALTHY
            if age < ttl:
                return
        if not self.master:
            return
        try:
            with rpc.dial(self._master_grpc()) as ch:
                resp = rpc.master_stub(ch).LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=ev.volume_id),
                    timeout=5,
                )
        except grpc.RpcError:
            return
        with ev.shard_locations_lock:
            for entry in resp.shard_id_locations:
                ev.shard_locations[entry.shard_id] = [
                    l.url for l in entry.locations
                ]
            ev.shard_locations_refresh_time = time.time()

    @staticmethod
    def _forget_shard_id(ev, shard_id: int) -> None:
        """Drop a shard's cached locations after a failed read; the
        next unhealthy-tier refresh re-learns them (forgetShardId,
        store_ec.go:211-216). The refresh clock is also zeroed so that
        refresh happens on the NEXT fetch, not after the tier TTL —
        found by the weedchaos lossy-gather scenario: one dropped
        connection used to blind every reconstruction needing this
        shard for up to 11 s (the unhealthy-tier TTL), turning 30%
        connection loss into sustained read unavailability."""
        with ev.shard_locations_lock:
            ev.shard_locations.pop(shard_id, None)
            ev.shard_locations_refresh_time = 0.0

    def _remote_shard_fetcher(self, ev):
        """fetch(shard_id, offset, size) against the EC volume's cached
        shard locations, forgetting locations whose reads fail. Safe to
        call concurrently (the reconstruction fan-out runs one fetch
        per missing shard in parallel)."""

        # refresh once up front: the reconstruction fan-out calls
        # fetch() from up to 13 threads at once, and each doing its own
        # cold-cache LookupEcVolume would hammer the master
        self._cached_lookup_ec_locations(ev)

        # capture trace context at factory time — the fan-out threads
        # have no ambient span, so the wire metadata carries the parent
        # (and the scrub plane tag when the scrubber built this fetcher)
        md = trace.grpc_metadata()
        # ...and the ambient deadline (docs/CHAOS.md): the degraded-read
        # fan-out runs on pool threads where the request's budget is not
        # ambient — capture it here so each remote read derives its
        # timeout from the REMAINING budget and stamps the hop header
        # (the shard holder 504-fast-rejects expired gathers instead of
        # decoding bytes the caller abandoned)
        factory_dl = _op_deadline.current()

        def read_from(url: str, shard_id: int, offset: int, size: int):
            host, _, port = url.partition(":")
            try:
                t_o = 10 if factory_dl is None else factory_dl.cap(10)
            except _op_deadline.DeadlineExceeded:
                return None  # budget spent: the gather fails, fast
            call_md = md
            if factory_dl is not None:
                call_md = tuple(md or ()) + (
                    (_op_deadline.DEADLINE_HEADER,
                     factory_dl.header_value()),
                )
            # two tries per holder: a flaky link (mid-stream RST, a
            # dropped proxy hop) kills individual connections, and a
            # fresh dial usually succeeds — distinguishing "this
            # transfer died" from "this holder is gone" is what keeps
            # lossy links from demoting healthy survivors
            for attempt in range(2):
                try:
                    with rpc.dial(f"{host}:{int(port) + 10000}") as ch:
                        chunks = [
                            r.data
                            for r in rpc.volume_stub(ch).VolumeEcShardRead(
                                pb.VolumeEcShardReadRequest(
                                    volume_id=ev.volume_id,
                                    shard_id=shard_id,
                                    offset=offset,
                                    size=size,
                                ),
                                timeout=t_o,
                                metadata=call_md,
                            )
                        ]
                    return b"".join(chunks)
                except grpc.RpcError:
                    continue
            return None

        def fetch(shard_id: int, offset: int, size: int):
            me = self._self_urls()
            for round_ in range(2):
                with ev.shard_locations_lock:
                    urls = list(ev.shard_locations.get(shard_id, []))
                attempted = False
                for url in urls:
                    if url in me:
                        continue
                    attempted = True
                    data = read_from(url, shard_id, offset, size)
                    if data is not None:
                        return data
                if attempted:
                    self._forget_shard_id(ev, shard_id)
                if round_ == 0:
                    # forgetting zeroed the refresh clock: re-learn the
                    # holders from the master NOW and give the shard one
                    # more chance inside this same request, instead of
                    # failing every reconstruction until a later fetch
                    # repopulates the cache
                    self._cached_lookup_ec_locations(ev)
            return None

        return fetch

    # ------------------------------------------------------------------
    # HTTP data path
    def _commit_write(self, vid: int, n, stages: dict | None = None):
        """The one write seam behind do_POST's Python path: (size,
        unchanged) via the group committer when one is installed
        (docs/QOS.md — batched pwritev + shared fsync window), else the
        classic per-needle store write."""
        if self.group_commit is None:
            return self.store.write_needle(vid, n, stages=stages)
        v = self.store.find_volume(vid)
        if v is None:
            raise NeedleNotFound(f"volume {vid} not found")
        _, size, unchanged = self.group_commit.write(v, n, stages=stages)
        return size, unchanged

    def _http_handler_class(self):
        server = self

        class Handler(FastHandler):
            def _reply(self, status, body=b"", headers=None):
                self.fast_reply(status, body, headers)

            def _json(self, obj, status=200):
                self._reply(status, json.dumps(obj).encode(), _JSON_HDR)

            def _route_shard_write(self, fid, body: bytes) -> bool:
                """-shardWrites: forward POST/DELETE for a worker-owned
                vid to that worker's internal listener. True = replied
                (routed); False = this process handles the write (it is
                the owner, took ownership back, or the worker died and
                ownership fell back here)."""
                if not server._shard_is_foreign(fid.volume_id):
                    return False
                if self.headers.get("x-shard-hop"):
                    # hop signaling is trusted from the loopback
                    # internal listener ONLY (workers proxy through
                    # it): honored from the public port, an anonymous
                    # client could force _ensure_owned per vid and
                    # strip write ownership from healthy workers
                    if self.server is server._internal_server:
                        # the owner could not serve this (unparsed
                        # form, manifest cascade, mid-commit volume):
                        # take the vid over and handle it here -
                        # routing back would loop
                        server._ensure_owned(fid.volume_id)
                        return False
                    self.headers.pop("x-shard-hop", None)
                result = server._proxy_to_writer(
                    server._shard_owner(fid.volume_id),
                    self.command,
                    self.path,
                    body,
                    self.headers,
                )
                if result is None:
                    # dead worker: permanent takeover, then local write
                    server._ensure_owned(fid.volume_id)
                    return False
                status, rheaders, data = result
                out = {
                    k: v
                    for k, v in rheaders.items()
                    if k not in ("connection", "keep-alive", "content-length")
                }
                self.fast_reply(status, data, out)
                return True

            def _parse_fid(self):
                """(FileId, query, filename, ext) from any of the
                reference's addressing forms (common.go:152
                parseURLPath + needle.go:149 ParsePath — comma/slash
                forms, optional extension and filename, `_delta`
                appendix fids). (None, None, "", "") = unparseable."""
                path, _, qs = self.path.partition("?")
                vid, fid_str, filename, ext, vid_only = parse_url_path(path)
                if vid_only or not fid_str:
                    return None, None, "", ""
                try:
                    return parse_path_fid(vid, fid_str), fast_query(qs), filename, ext
                except ValueError:
                    return None, None, "", ""

            def _check_write_auth(self) -> bool:
                """True = allowed; shared candidate/claim logic lives in
                write_path.check_write_auth (the -shardWrites workers
                run the same check on their local writes)."""
                err = write_path.check_write_auth(
                    server.guard, self.path, self.headers,
                    self.client_address[0],
                )
                if err is None:
                    return True
                self._json({"error": err}, 401)
                return False

            def do_GET(self):
                url_path = self.path.partition("?")[0]
                if url_path in ("/", "/ui/index.html"):
                    return self._reply(
                        200,
                        server._render_ui().encode(),
                        {"Content-Type": "text/html; charset=utf-8"},
                    )
                if url_path == "/__shard/taken":
                    # write-sharding control surface (workers sync the
                    # taken-over vid list at startup) — loopback
                    # internal listener ONLY; on the public port an
                    # anonymous client must not even learn it exists
                    if self.server is not server._internal_server:
                        return self._json({"error": "not found"}, 404)
                    return self._json(sorted(server._shard_taken))
                if url_path == "/status":
                    from seaweedfs_tpu import images

                    hb = server.store.collect_heartbeat()
                    return self._json(
                        {
                            "Version": "seaweedfs_tpu",
                            "Volumes": len(hb.volumes),
                            "EcVolumes": len(hb.ec_shards),
                            # scrub plane: quarantined shards are no
                            # longer silent — operators (and the shell's
                            # scrub.status) see them here, the master
                            # sees them via ScrubStat heartbeat rows
                            # list() snapshots: the scrub thread (or a
                            # foreground quarantine) mutates these dicts
                            # concurrently with this handler thread
                            "QuarantinedShards": {
                                str(vid): sorted(list(per_vid))
                                for vid, per_vid in list(
                                    server.store.quarantined.items()
                                )
                            },
                            "Scrub": (
                                server.scrub.status()
                                if server.scrub is not None
                                else {"Disabled": True}
                            ),
                            # health plane (docs/HEALTH.md): local
                            # degradation state + the handoff spool
                            "LameDuck": server.watchdog.lame_duck,
                            "Draining": server.draining,
                            "IoErrors": server.watchdog.io_errors,
                            "HandoffPending": server.hints.pending(),
                            "Resizing": (
                                "enabled"
                                if images.resizing_enabled()
                                else "disabled"
                            ),
                            # C serving-edge counters (docs/SERVING.md):
                            # weedload scrapes these for its fast-path
                            # hit / 304 / plan-cache ratios
                            "ServeStats": _native_serve.serve_stats(),
                        }
                    )
                if url_path == "/scrub/status":
                    if server.scrub is None:
                        return self._json({"Disabled": True})
                    return self._json(server.scrub.status())
                if url_path == "/scrub/trigger":
                    # operator surface (scrub.trigger shell command):
                    # kick a sweep now, optionally one volume first
                    if server.scrub is None:
                        return self._json({"error": "scrub disabled"}, 400)
                    q = fast_query(self.path.partition("?")[2])
                    vid_arg = q.get("volumeId", "")
                    try:
                        vid = int(vid_arg) if vid_arg else None
                    except ValueError:
                        return self._json(
                            {"error": f"bad volumeId {vid_arg!r}"}, 400
                        )
                    server.scrub.trigger(vid)
                    return self._json({"triggered": True, "volumeId": vid})
                if url_path == "/tier/status":
                    # lifecycle tiering (docs/TIERING.md): per-volume
                    # local/remote shard state + mtimes — the master's
                    # TierScheduler polls this for its age signal
                    from seaweedfs_tpu.tier.ec_tier import tier_status

                    return self._json(tier_status(server.store))
                if url_path == "/ec/quarantine":
                    # operator surface (and tests/faults.DeadShard): put
                    # one mounted EC shard out of service NOW — the
                    # degraded-read drill lever (docs/SCRUB.md); same
                    # rename-to-.bad path the scrubber takes, so the
                    # repair plane regenerates it like real damage
                    q = fast_query(self.path.partition("?")[2])
                    try:
                        vid = int(q.get("volumeId", ""))
                    except ValueError:
                        return self._json({"error": "bad volumeId"}, 400)
                    ev = server.store.find_ec_volume(vid)
                    if ev is None:
                        return self._json(
                            {"error": f"ec volume {vid} not here"}, 404
                        )
                    sid_arg = q.get("shard", "")
                    try:
                        sid = int(sid_arg) if sid_arg else ev.shard_ids()[0]
                    except (ValueError, IndexError):
                        return self._json({"error": "bad shard"}, 400)
                    ok = ev.quarantine_shard(sid, "operator: /ec/quarantine")
                    return self._json(
                        {"volumeId": vid, "shard": sid, "quarantined": ok}
                    )
                if url_path == "/metrics":
                    from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY

                    body = DEFAULT_REGISTRY.render_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return self.wfile.write(body)
                # stage timings for the traced threaded GET arm, named
                # identically to the C fast path's SERVE_STAGES
                # (parse/resolve/send) so a blackbox wide-event reads
                # the same whichever arm served it (the weedscope twin
                # of the POST arm's parse/assemble/crc/pwrite/reply)
                req_span = getattr(self, "_trace_span", None)
                stages = {} if req_span is not None else None
                t_stage = time.perf_counter() if stages is not None else 0.0
                fid, q, url_filename, url_ext = self._parse_fid()
                if stages is not None:
                    now_pc = time.perf_counter()
                    stages["parse"] = now_pc - t_stage
                    t_stage = now_pc

                def _staged_exit(status, body=b"", headers=None, obj=None):
                    # error/redirect/not-modified exits carry the same
                    # stage fields as the C fast path (resolve ends at
                    # the verdict, send covers the reply write): a 404
                    # wide-event reads identically on both arms
                    if stages is None:
                        if obj is not None:
                            return self._json(obj, status)
                        return self._reply(status, body, headers)
                    t_send = time.perf_counter()
                    stages["resolve"] = t_send - t_stage
                    if obj is not None:
                        self._json(obj, status)
                    else:
                        self._reply(status, body, headers)
                    stages["send"] = time.perf_counter() - t_send
                    req_span.add_stages(stages)

                if fid is None:
                    return _staged_exit(400, obj={"error": "invalid file id"})
                if self.headers.get(qos.HEDGE_HEADER):
                    # QoS plane: a tied (hedged) read — count it and tag
                    # the span so trace.dump shows which arm this was;
                    # if the client's other attempt wins, its socket
                    # close is the cancel (the reply write fails and
                    # this connection tears down quietly)
                    from seaweedfs_tpu.stats.metrics import HEDGE_SERVED

                    HEDGE_SERVED.labels("volume").inc()
                    hedge_span = getattr(self, "_trace_span", None)
                    if hedge_span is not None:
                        hedge_span.annotate("hedge", 1)
                try:
                    v = server.store.find_volume(fid.volume_id)
                    if v is not None:
                        server._shard_refresh(v)
                        n = v.read_needle(fid.key, cookie=fid.cookie)
                    else:
                        ev = server.store.find_ec_volume(fid.volume_id)
                        if ev is None:
                            # not local: redirect the reader to an owning
                            # node (volume_server_handlers_read.go:60-77)
                            target = server._redirect_target(fid.volume_id)
                            if target:
                                return _staged_exit(
                                    302,
                                    b"",
                                    {"Location": f"http://{target}{self.path}"},
                                )
                            return _staged_exit(
                                404, obj={"error": "volume not found"}
                            )
                        n = ev.read_needle(
                            fid.key, fetch=server._remote_shard_fetcher(ev)
                        )
                        if n.cookie != fid.cookie:
                            raise CookieMismatch("cookie mismatch")
                except NeedleNotFound:
                    return _staged_exit(404)
                except CookieMismatch:
                    return _staged_exit(404)
                except NotEnoughShards as e:
                    return _staged_exit(500, obj={"error": str(e)})
                except OSError as e:
                    # disk watchdog (docs/HEALTH.md): EIO on the read
                    # path strikes toward lame-duck mode; a 500 beats a
                    # silently torn connection either way
                    if not server.watchdog.note_io_error(e):
                        raise
                    return _staged_exit(
                        500, obj={"error": f"read failed: {e}"}
                    )
                # serve-first: stamp the arbiter so background planes
                # (rebuild/replication/handoff/tier) yield to foreground
                # reads; the per-volume counter is the tier scheduler's
                # access-temperature signal (scraped via /metrics)
                get_arbiter().note_serve()
                VOLUME_READS.labels(str(fid.volume_id)).inc()
                if n.is_chunked_manifest():
                    return self._serve_chunked_manifest(n)
                # conditional gets: If-Modified-Since (RFC 1123, like
                # the reference's time.Parse(http.TimeFormat) check at
                # volume_server_handlers_read.go:102-112) and ETag
                if n.has_last_modified_date():
                    ims = self.headers.get("if-modified-since")
                    if ims:
                        from email.utils import parsedate_to_datetime

                        try:
                            t = parsedate_to_datetime(ims).timestamp()
                        except (TypeError, ValueError):
                            t = None
                        if t is not None and t >= n.last_modified:
                            return _staged_exit(304)
                data = bytes(n.data)
                if self.headers.get("etag-md5") == "True":
                    # opt-in md5 validator (crc.go:33 n.MD5 + ETag-MD5);
                    # picked BEFORE the If-None-Match compare so md5
                    # revalidations can actually 304
                    import hashlib

                    etag = f'"{hashlib.md5(data).hexdigest()}"'
                else:
                    etag = f'"{n.etag()}"'
                # RFC 9110 §13.1.2: weak validators (W/"…"), comma
                # lists, and `*` all revalidate — not just the exact
                # strong match (the C fast path's weed_etag_match runs
                # the same scanner; the identity tests diff them)
                if etag_matches(self.headers.get("If-None-Match", ""), etag):
                    return _staged_exit(304)
                headers = {"ETag": etag, "Content-Type": "application/octet-stream"}
                # URL filename wins; else the stored name; ext feeds the
                # mime guess and the resizer (read handler :138-150)
                fname = url_filename
                if not fname and n.has_name() and n.name:
                    fname = n.name.decode("latin-1")
                ext = url_ext or (os.path.splitext(fname)[1] if fname else "")
                if n.has_mime() and n.mime and not n.mime.startswith(
                    b"application/octet-stream"
                ):
                    headers["Content-Type"] = n.mime.decode("latin-1")
                elif ext:
                    import mimetypes

                    guessed = mimetypes.types_map.get(ext.lower())
                    if guessed:
                        headers["Content-Type"] = guessed
                if fname:
                    disp = "inline"
                    if q.get("dl", "").lower() in ("true", "1"):
                        disp = "attachment"
                    escaped = fname.replace("\\", "\\\\").replace('"', '\\"')
                    headers["Content-Disposition"] = (
                        f'{disp}; filename="{escaped}"'
                    )
                if n.has_last_modified_date():
                    headers["Last-Modified"] = _http_date(n.last_modified)
                if n.has_pairs() and n.pairs:
                    # stored extended pairs surface as response headers
                    # (read handler :123-133) — minus framing headers a
                    # hostile uploader could use to desync keep-alive
                    try:
                        pair_obj = json.loads(n.pairs)
                        items = (
                            pair_obj.items() if isinstance(pair_obj, dict) else ()
                        )
                        for k, pv in items:
                            if str(k).lower() in (
                                "content-length", "connection",
                                "transfer-encoding", "content-encoding",
                            ):
                                continue
                            headers[str(k)] = str(pv)
                    except ValueError:
                        pass
                try:
                    width = int(q.get("width", "0") or 0)
                    height = int(q.get("height", "0") or 0)
                except ValueError:
                    width = height = 0
                if n.is_gzipped() and ext != ".gz":
                    # stored-gzipped: pass through to gzip-accepting
                    # clients, transparently decompress for the rest
                    # (read handler :152-162); an explicit .gz URL gets
                    # the raw bytes. Resizes always decompress — the
                    # resizer needs pixels, not a gzip stream.
                    if (
                        not (width or height)
                        and "gzip" in self.headers.get("accept-encoding", "")
                    ):
                        headers["Content-Encoding"] = "gzip"
                    else:
                        from seaweedfs_tpu.util.compression import try_gunzip

                        decoded = try_gunzip(data)
                        if decoded is data:
                            wlog.warning("ungzip %s: corrupt stream", self.path)
                        data = decoded
                # on-read image resizing (?width=&height=&mode=,
                # volume_server_handlers_read.go:224 images.Resized);
                # unparseable dims serve the original, as the reference
                if width or height:
                    rext = ext
                    if not rext and headers["Content-Type"].startswith("image/"):
                        rext = "." + headers["Content-Type"].split("/")[1]
                    from seaweedfs_tpu import images

                    if images.is_image_ext(rext):
                        data, _, _ = images.resized(rext, data, width, height, q.get("mode", ""))
                        headers.pop("ETag", None)  # derived variant
                if stages is None:
                    return self._serve_maybe_ranged(data, headers)
                now_pc = time.perf_counter()
                stages["resolve"] = now_pc - t_stage
                self._serve_maybe_ranged(data, headers)
                stages["send"] = time.perf_counter() - now_pc
                req_span.add_stages(stages)

            def _serve_maybe_ranged(self, data: bytes, headers: dict):
                """Full 200 or single-range 206 per the Range header
                (volume_server_handlers_read.go serves ranges via
                http.ServeContent; suffix and open-ended forms too).
                Takes OWNERSHIP of `headers` (callers pass a fresh
                per-request dict, never a shared constant): no-Range
                requests — the hot read path — mutate it in place
                instead of copying."""
                rng = self.headers.get("range")
                if not rng:
                    headers["Accept-Ranges"] = "bytes"
                    return self._reply(200, data, headers)
                from seaweedfs_tpu.util.http_range import (
                    RangeNotSatisfiable,
                    parse_range,
                )

                headers = dict(headers)
                headers["Accept-Ranges"] = "bytes"
                total = len(data)
                try:
                    span = parse_range(rng, total)
                except RangeNotSatisfiable:
                    return self._reply(
                        416, b"", {"Content-Range": f"bytes */{total}"}
                    )
                if span is None:
                    return self._reply(200, data, headers)
                start, end = span
                headers["Content-Range"] = f"bytes {start}-{end}/{total}"
                self._reply(206, data[start : end + 1], headers)

            def _serve_chunked_manifest(self, n: Needle):
                """Chunk-manifest fan-in: stream each chunk fid in offset
                order without buffering the whole file
                (volume_server_handlers_read.go:171, ChunkedFileReader)."""
                raw = _needle_manifest_bytes(n)
                chunks = _parse_manifest_chunks(raw)
                if chunks is None:
                    return self._json({"error": "invalid chunk manifest"}, 500)
                manifest = json.loads(raw)
                # Content-Length must match what we actually stream, so
                # it comes from the validated chunk sizes, never the
                # client-declared manifest "size"
                total = sum(c["size"] for c in chunks)
                headers = {"Content-Type": "application/octet-stream"}
                if manifest.get("mime"):
                    headers["Content-Type"] = manifest["mime"]
                if manifest.get("name"):
                    headers["Content-Disposition"] = (
                        f'inline; filename="{manifest["name"]}"'
                    )
                self.send_response(200)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(total))
                self.end_headers()
                if self.command == "HEAD":
                    return
                for c in chunks:
                    piece = server._fetch_fid(c["fid"])
                    if piece is None:
                        # headers already sent; truncate the connection so
                        # the client sees a short read, not silent corruption
                        self.close_connection = True
                        return
                    self.wfile.write(piece)

            do_HEAD = do_GET

            def _shed_unwritable(self) -> bool:
                """weedguard graceful degradation (docs/HEALTH.md):
                a lame-duck (disk watchdog tripped) or draining node
                sheds NEW writes with 503 + Retry-After — reads keep
                flowing, the master has already stopped assigning
                here, and a healthy primary's replica fan-out turns
                the 503 into a handoff hint instead of a failed
                write."""
                if not (server.watchdog.lame_duck or server.draining):
                    return False
                why = (
                    "lame-duck (disk errors)"
                    if server.watchdog.lame_duck
                    else "draining"
                )
                self._reply(
                    503,
                    json.dumps(
                        {"error": f"node is read-only: {why}"}
                    ).encode(),
                    _JSON_HDR + b"Retry-After: 1\r\n",
                )
                return True

            def _tier_move(self):
                """POST /tier/move?volumeId=&direction=out|in
                [&destination=type.id] — the TierScheduler's (and
                tier.move shell command's) verb. Runs the move inline
                under the request's ambient deadline; the engine
                charges the bandwidth arbiter's "tier" claimant as
                bytes stream, so a scan-wide fan-in cannot stampede."""
                from seaweedfs_tpu.tier import ec_tier
                from seaweedfs_tpu.tier.rules import tier_enabled

                if not tier_enabled():
                    return self._json(
                        {"error": "tiering disabled (WEED_TIER=0)"}, 403
                    )
                q = fast_query(self.path.partition("?")[2])
                try:
                    vid = int(q.get("volumeId", ""))
                except ValueError:
                    return self._json({"error": "bad volumeId"}, 400)
                direction = q.get("direction", "out")
                try:
                    if direction == "out":
                        dest = q.get("destination", "")
                        if not dest:
                            return self._json(
                                {"error": "destination required"}, 400
                            )
                        result = ec_tier.tier_out_ec(
                            server.store, vid, dest, stop=server._stop
                        )
                    elif direction == "in":
                        result = ec_tier.tier_in_ec(
                            server.store, vid, stop=server._stop
                        )
                    else:
                        return self._json(
                            {"error": f"bad direction {direction!r}"}, 400
                        )
                except (ValueError, KeyError) as e:
                    return self._json({"error": str(e)}, 404)
                except (OSError, RuntimeError) as e:
                    return self._json({"error": str(e)}, 500)
                return self._json(result)

            def do_POST(self):
                if self.path.partition("?")[0] == "/tier/move":
                    return self._tier_move()
                fid, q, url_filename, _url_ext = self._parse_fid()
                if fid is None:
                    return self._json({"error": "invalid file id"}, 400)
                if not self._check_write_auth():
                    return
                if self._shed_unwritable():
                    return
                # serve-first: foreground writes also push background
                # planes (rebuild/replication/handoff/tier) into their
                # yield window
                get_arbiter().note_serve()
                length = int(self.headers.get("content-length", "0"))
                body = self.rfile.read(length)
                if server.shard_writes:
                    routed = self._route_shard_write(fid, body)
                    if routed:
                        return
                # one-pass C hot loop (native/post.c): extraction →
                # needle → CRC → pwrite → reply bytes, GIL released;
                # None = this request needs the Python path below
                # (which stays byte-identical for what C handles).
                # Both branches converge on ONE replicate-then-reply
                # tail so the fan-out/error contract cannot drift.
                # `stages` (tracing plane): both paths emit the same
                # parse/assemble/crc/pwrite/reply names, attached to
                # the mini loop's volume.post span (handed to us as
                # _trace_span by serve_connection — reading the warm
                # handler attr keeps trace-module objects off the hot
                # path)
                req_span = getattr(self, "_trace_span", None)
                stages = {} if req_span is not None else None
                try:
                    if server.group_commit is not None:
                        # QoS group commit (docs/QOS.md): the C one-call
                        # append can't join a commit window (and fsync-only
                        # mode needs the post-write flush), so the fast
                        # path declines wholesale while a committer is
                        # installed — the Python path below routes through
                        # it and stays byte-identical
                        reply = None
                    else:
                        reply = write_path.try_native_post(
                            server.store.find_volume(fid.volume_id),
                            fid,
                            q,
                            body,
                            self.headers,
                            url_filename,
                            server.fix_jpg_orientation,
                            stages=stages,
                        )
                except OSError as e:
                    # disk watchdog (docs/HEALTH.md): an EIO/ENOSPC on
                    # the append path strikes toward lame-duck mode and
                    # fails THIS write loudly; anything else (deadline,
                    # connection) keeps its existing handling
                    if not server.watchdog.note_io_error(e):
                        raise
                    return self._json({"error": f"write failed: {e}"}, 500)
                if reply is None:
                    n, fname, err = write_path.build_upload_needle(
                        fid,
                        q,
                        body,
                        self.headers,
                        url_filename,
                        server.fix_jpg_orientation,
                        stages=stages,
                    )
                    if err is not None:
                        return self._json({"error": err}, 400)
                    try:
                        size, unchanged = server._commit_write(
                            fid.volume_id, n, stages=stages
                        )
                    except NeedleNotFound:
                        return self._json({"error": "volume not found"}, 404)
                    except (VolumeReadOnly, CookieMismatch) as e:
                        return self._json({"error": str(e)}, 409)
                    except OSError as e:
                        if not server.watchdog.note_io_error(e):
                            raise
                        return self._json(
                            {"error": f"write failed: {e}"}, 500
                        )
                    t_reply = time.perf_counter() if stages is not None else 0.0
                    reply = (
                        b'{"name": %s, "size": %d, "eTag": "%s"}'
                        % (_esc_json(fname).encode(), size, n.etag().encode())
                    )
                    if stages is not None:
                        stages["reply"] = time.perf_counter() - t_reply
                if stages:
                    req_span.add_stages(stages)
                if q.get("type") != "replicate":
                    err = server._replicate(fid, q, "POST", body, self.headers)
                    if err:
                        return self._json({"error": err}, 500)
                self._reply(201, reply, _JSON_HDR)

            def do_DELETE(self):
                fid, q, _fn, _ext = self._parse_fid()
                if fid is None:
                    return self._json({"error": "invalid file id"}, 400)
                if not self._check_write_auth():
                    return
                if self._shed_unwritable():
                    return
                if server.shard_writes and self._route_shard_write(fid, b""):
                    return
                n = Needle(cookie=fid.cookie, id=fid.key)
                try:
                    v = server.store.find_volume(fid.volume_id)
                    if v is not None:
                        existing = v.read_needle(fid.key, cookie=fid.cookie)
                        size = server.store.delete_needle(fid.volume_id, n)
                    else:
                        ev = server.store.find_ec_volume(fid.volume_id)
                        if ev is None:
                            return self._json({"error": "volume not found"}, 404)
                        # same cookie gate as the normal-volume branch
                        existing = ev.read_needle(
                            fid.key,
                            fetch=server._remote_shard_fetcher(ev),
                        )
                        if existing.cookie != fid.cookie:
                            raise CookieMismatch("cookie mismatch")
                        ev.delete_needle(fid.key)
                        size = 0
                except NeedleNotFound:
                    return self._json({"size": 0}, 404)
                except CookieMismatch as e:
                    return self._json({"error": str(e)}, 409)
                if existing.is_chunked_manifest():
                    # cascade: delete every chunk the manifest points at
                    # (volume_server_handlers_write.go DeleteHandler)
                    for c in _parse_manifest_chunks(_needle_manifest_bytes(existing)) or []:
                        server._delete_fid(c["fid"])
                if q.get("type") != "replicate":
                    err = server._replicate(
                        fid, q, "DELETE", b"", self.headers
                    )
                    if err:
                        return self._json({"error": err}, 500)
                self._json({"size": size}, 202)

        return Handler

    # ------------------------------------------------------------------
    # zero-copy GET fast path (docs/SERVING.md): the C epoll loop calls
    # this resolver for plain GET/HEAD requests; it maps a bare
    # /<vid>,<fid> path to a pre-formatted response the loop finishes
    # without ever entering do_GET — small records from one pread (CRC
    # verified), large ones zero-copy via sendfile from a dup'd fd.
    # Anything with richer semantics (query params, filename/extension
    # segments, EC volumes, redirects, gzip/name/mime/ttl/pairs/
    # chunk-manifest needles, conditional headers — those never reach
    # here, the C loop hands them off) returns None and the request
    # takes the threaded Python path, whose responses are byte-
    # identical for everything this path does serve (the shared
    # reply_prefix/parse_range helpers make that true by construction).
    def _make_fast_resolver(self):
        from seaweedfs_tpu.util.httpd import reply_prefix
        from seaweedfs_tpu.util.native_serve import generation as _generation

        find_volume = self.store.find_volume
        shard_refresh = self._shard_refresh
        plan_core = make_needle_plan_core()
        prefix_304 = reply_prefix(304)
        # a 404 carries no validator (etag None): the C loop can never
        # answer a conditional against it, matching do_GET (which 404s
        # before the ETag compare)
        not_found = (404, reply_prefix(404), b"", -1, 0, 0,
                     None, prefix_304, 0, 0)
        # plan caching is sound only while EVERY .dat mutation happens
        # in THIS process (the generation hooks in storage/volume.py
        # are process-local atomics): -shardWrites workers append from
        # sibling processes the lead only notices inside the resolve
        # path — which a cache hit skips — so they disable it. Plain
        # -workers read processes never write, so the lead stays
        # cacheable under them.
        cacheable = 0 if self.shard_writes else 1

        def resolver(path, rng, head_only):
            adm = self.admission
            if adm is not None and not getattr(adm, "shared", False):
                # a per-process token bucket runs in the mini loop's
                # dispatch funnel only; declining routes every request
                # through it. The SHARED (shm) bucket is enforced by
                # the C loop itself, so the fast path stays native.
                return None
            if "?" in path:
                return None
            vid_s, fid_s, filename, ext, vid_only = parse_url_path(path)
            if vid_only or not fid_s or filename or ext:
                return None
            try:
                fid = parse_path_fid(vid_s, fid_s)
            except ValueError:
                return None  # Python's invalid-file-id 400 JSON
            v = find_volume(fid.volume_id)
            if v is None:
                return None  # EC / redirect lookup: Python path
            if v.version not in (2, 3):
                return None
            # generation BEFORE the map read: a write landing between
            # here and the pread bumps past `gen`, so the C loop
            # refuses to cache the (now possibly stale) plan
            gen = _generation()
            shard_refresh(v)
            out = plan_core(v, fid, rng, head_only, gen, cacheable)
            if out is None:
                return None
            if out[0] in ("notfound", "cookie"):
                return not_found  # do_GET 404s both with an empty body
            return out[1]

        return resolver

    def _self_urls(self) -> set[str]:
        """Every address the master may report THIS server under: the
        bind address and (with -announce) the advertised proxy/NAT
        address. Self-exclusion checks must match BOTH — an announced
        primary that only filtered its bind identity would replicate
        every write to itself through the announced hop (found by the
        weedchaos bench: the duplicate append also coupled write
        success to the node's own proxy being up)."""
        me = {f"{self.host}:{self.port}"}
        me.add(f"{self.announce_host}:{self.announce_port}")
        return me

    def _redirect_target(self, vid: int) -> str | None:
        """Another server that can serve this vid: a replica holder, or
        any EC shard holder learned from the master."""
        me = self._self_urls()
        for url in self._lookup_locations(vid) or []:
            if url not in me:
                return url
        if not self.master:
            return None
        try:
            with rpc.dial(self._master_grpc()) as ch:
                resp = rpc.master_stub(ch).LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid)
                )
            for entry in resp.shard_id_locations:
                for loc in entry.locations:
                    if loc.url != me:
                        return loc.url
        except grpc.RpcError:
            pass
        return None

    def _fetch_fid(self, fid_str: str) -> bytes | None:
        """Resolve a chunk fid (local store first, then master lookup +
        HTTP GET from the owning peer)."""
        import urllib.request

        try:
            fid = FileId.parse(fid_str)
        except ValueError:
            return None
        v = self.store.find_volume(fid.volume_id)
        if v is not None:
            try:
                n = v.read_needle(fid.key, cookie=fid.cookie)
            except (NeedleNotFound, CookieMismatch):
                return None
            if n.is_gzipped():
                from seaweedfs_tpu.util.compression import try_gunzip

                return try_gunzip(bytes(n.data))
            return n.data
        locations = self._lookup_locations(fid.volume_id) or []
        for url in locations:
            try:
                # weedlint: ignore[no-deadline] — single bounded 10 s replica hop; TODO fold into http_call so replica reads inherit the request budget
                with urllib.request.urlopen(f"http://{url}/{fid_str}", timeout=10) as r:
                    return r.read()
            except OSError:
                continue
        return None

    def _delete_fid(self, fid_str: str) -> None:
        """Cascade-delete one chunk fid through the HTTP DELETE path so
        the handler's replication fan-out reaches every replica (a
        local-only store delete would orphan replica copies)."""
        import urllib.request

        try:
            fid = FileId.parse(fid_str)
        except ValueError:
            return
        mine = self._self_urls()
        urls = [u for u in (self._lookup_locations(fid.volume_id) or [])
                if u not in mine]
        if self.store.find_volume(fid.volume_id) is not None:
            # dial ourselves by the BIND address, never the announced
            # hop (and never twice)
            urls = [f"{self.host}:{self.port}"] + urls
        for url in urls:
            try:
                req = urllib.request.Request(f"http://{url}/{fid_str}", method="DELETE")
                if self.guard is not None and self.guard.signing_key:
                    # server-initiated cascade: sign our own write token
                    req.add_header(
                        "Authorization", f"BEARER {self.guard.sign_write(fid_str)}"
                    )
                # weedlint: ignore[no-deadline] — single bounded 10 s replica-delete hop; the cascade itself is the retry surface
                urllib.request.urlopen(req, timeout=10).read()
                return
            except OSError:
                continue

    # --- -shardWrites: volume-ownership write sharding -----------------
    def _shard_owner(self, vid: int) -> int:
        return vid % self.n_writers

    def _writer_internal_addr(self, writer_index: int) -> str:
        return f"127.0.0.1:{self.internal_port + writer_index}"

    def _shard_is_foreign(self, vid: int) -> bool:
        """True while a WORKER owns this vid's writes (so this process
        must route writes and refresh before reads)."""
        return (
            self.shard_writes
            and self._shard_owner(vid) != 0
            and vid not in self._shard_taken
        )

    def _shard_refresh(self, v) -> None:
        """Replay the owner's .idx tail before serving a read of a
        worker-owned volume (read-your-writes across processes)."""
        if self._shard_is_foreign(v.id):
            v.refresh_from_idx()

    def _ensure_owned(self, vid: int) -> None:
        """Take a vid's write ownership back from its worker before a
        file-rewriting admin op (vacuum, EC encode, readonly, delete,
        copy). Permanent: ownership never returns to the worker (the
        worker proxies that vid's writes here from then on). The
        handshake is synchronous — the op must not start while the
        worker could still append; a connection refusal means the
        worker is dead, which is an implicit release."""
        if not self.shard_writes:
            return
        owner = self._shard_owner(vid)
        if owner == 0:
            return
        with self._shard_lock:
            if vid in self._shard_taken:
                return
            vlock = self._shard_vid_locks.setdefault(vid, threading.Lock())
        with vlock:
            with self._shard_lock:
                if vid in self._shard_taken:
                    return
            import urllib.request

            try:
                # weedlint: ignore[no-deadline] — localhost worker-to-worker control hop, 10 s cap; no request budget exists on this path
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://{self._writer_internal_addr(owner)}"
                        f"/__shard/release?vid={vid}",
                        method="POST",
                    ),
                    timeout=10,
                ).close()
            except ConnectionError:
                pass  # dead worker: implicit release
            except OSError as e:
                if not isinstance(getattr(e, "reason", None), ConnectionError):
                    raise  # alive-but-failing worker: do NOT double-write
            v = self.store.find_volume(vid)
            if v is not None:
                v.refresh_from_idx()
            with self._shard_lock:
                # weedlint: ignore[race-check-then-act] — the per-vid vlock (from _shard_vid_locks, invisible to the lint's self-attr span tracking) is held continuously from the re-check through the handshake to this add; _shard_lock only guards the set's memory
                self._shard_taken.add(vid)

    def _proxy_to_writer(
        self, writer_index: int, method: str, path: str, body: bytes, headers
    ):
        """Forward a write to its owning worker's internal listener.
        Returns (status, headers, data) or None when unreachable."""
        from seaweedfs_tpu.client.operation import _drop_conn, _pooled_conn

        addr = self._writer_internal_addr(writer_index)
        fwd = {
            k: v
            for k, v in headers.items()
            if k not in ("connection", "keep-alive", "content-length", "host")
        }
        # re-stamp the trace header with THIS hop's span so the worker's
        # span parents here, not at the client's original header
        trace.inject(fwd)
        try:
            c, reused = _pooled_conn(addr, 30.0)
            try:
                c.send_request(method, path, body, fwd)
                status, rheaders, data, will_close = c.read_response(method)
            except OSError:
                _drop_conn(addr)
                if not reused:
                    raise
                c, _ = _pooled_conn(addr, 30.0)
                c.send_request(method, path, body, fwd)
                status, rheaders, data, will_close = c.read_response(method)
            if will_close:
                _drop_conn(addr)
            return status, rheaders, data
        except OSError:
            _drop_conn(addr)
            return None

    def _replicate(self, fid: FileId, q: dict, method: str, body: bytes, headers: dict) -> str | None:
        """Fan the write to replica peers (store_replicate.go:44-80).

        weedguard (docs/HEALTH.md): a peer that fails at the transport
        level or with a 5xx gets the request durably spooled as a
        handoff hint instead of failing the whole write — the hint is
        published via util/durable BEFORE this returns (i.e. before the
        client is acked), and the handoff agent replays it once the
        peer heals. WEED_HEALTH=0 / WEED_HANDOFF=0 restore the
        all-or-error contract wholesale."""
        v = self.store.find_volume(fid.volume_id)
        if v is None or v.super_block.replica_placement.copy_count <= 1:
            return None
        if not self.master:
            return None
        all_locations = self._lookup_locations(fid.volume_id)
        if all_locations is None:
            return "replication lookup failed"
        mine = self._self_urls()
        locations = [u for u in all_locations if u not in mine]
        from seaweedfs_tpu.server import handoff as handoff_mod

        on_fail = None
        if handoff_mod.handoff_enabled():
            def on_fail(url, path_q, err, status):
                ok = self.hints.write_hint(
                    url,
                    method,
                    path_q,
                    body if method == "POST" else b"",
                    handoff_mod.keep_headers(headers),
                )
                if ok:
                    wlog.warning(
                        "handoff: replica %s failed (%s); write hinted "
                        "for replay on heal", url, err,
                    )
                return ok

        return write_path.replicate_to_peers(
            fid, q, method, body, headers, locations, on_fail=on_fail
        )
    def start(self) -> None:
        self._grpc_server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        self._grpc_server.add_generic_rpc_handlers(
            (rpc.servicer_handler(rpc.VOLUME_SERVICE, rpc.VOLUME_METHODS, self),)
        )
        rpc.add_port(self._grpc_server, f"{self.host}:{self.grpc_port}")
        self._grpc_server.start()
        from seaweedfs_tpu.util.httpd import ReusePortWeedHTTPServer

        handler = self._http_handler_class()
        server_cls = ReusePortWeedHTTPServer if self.reuse_port else WeedHTTPServer
        self._http_server = server_cls((self.host, self.port), handler)
        # tracing plane: the mini request loop mints/inherits a span per
        # request, labeled with this daemon's role and address
        self._http_server.trace_name = "volume"
        self._http_server.trace_node = f"{self.host}:{self.port}"
        # event-driven serving core (docs/SERVING.md): the epoll loop
        # answers plain needle GETs through this resolver without
        # touching the handler; the knobs bound keep-alive lifetimes on
        # both serving paths
        self._http_server.fast_resolver = self._make_fast_resolver()
        self._http_server.serve_idle_ms = self.serve_idle_ms
        self._http_server.serve_max_reqs = self.serve_max_reqs
        # QoS plane: the mini loop counts in-flight dispatches (heartbeat
        # load signal) and runs per-client admission when configured
        self._http_server.load_tracker = self.load
        self._http_server.admission = self.admission
        threading.Thread(target=self._http_server.serve_forever, daemon=True).start()
        if self.internal_port:
            self._internal_server = WeedHTTPServer(
                ("127.0.0.1", self.internal_port), handler
            )
            self._internal_server.trace_name = "volume"
            self._internal_server.trace_node = f"{self.host}:{self.port}"
            # no idle/max-req knobs here: the -workers proxy pool keeps
            # long-lived internal connections by design
            self._internal_server.fast_resolver = self._http_server.fast_resolver
            threading.Thread(
                target=self._internal_server.serve_forever, daemon=True
            ).start()
        if self.master:
            self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
            self._hb_thread.start()
        # handoff agent (docs/HEALTH.md): replays spooled replica
        # writes once their target heals; idles cheaply when the spool
        # is empty (and drains hints left by a previous process life)
        self.handoff.start()
        if self.scrub is not None:
            self.scrub.start()
        # telemetry plane: continuous sampling profiler behind
        # /debug/profile (WEED_PROF=0 opts out)
        from seaweedfs_tpu.telemetry import profiler

        profiler.ensure_started()

    def drain(self, timeout: float = 30.0) -> None:
        """SIGTERM graceful drain (docs/HEALTH.md runbook): announce
        `draining` on an immediate beat — the master excludes this node
        from write assignment and the RepairScheduler starts moving
        data off — shed new writes with 503, let in-flight requests
        finish (bounded by `timeout`), then stop(): the heartbeat
        stream teardown deregisters the node cleanly."""
        self.draining = True
        self._hb_wake.set()  # the flag rides the NEXT beat, now
        wlog.warning(
            "volume %s:%d draining: writes shed, waiting for %d "
            "in-flight request(s)", self.host, self.port,
            self.load.inflight(),
        )
        # one beat RTT so the master sees the flag before we exit
        deadline = time.time() + timeout
        time.sleep(min(2 * self.heartbeat_interval, 2.0))
        while time.time() < deadline and self.load.inflight() > 0:
            time.sleep(0.05)
        # last chance to deliver spooled hints while we are still up
        try:
            self.handoff.run_once()
        except Exception:  # noqa: BLE001 — drain must complete anyway
            pass
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._hb_wake.set()  # unblock the heartbeat generator's wait
        self.handoff.stop()
        if self.scrub is not None:
            self.scrub.stop()
        if self._metrics_push is not None:
            self._metrics_push.stop_event.set()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._internal_server:
            self._internal_server.shutdown()
            self._internal_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        self.store.close()
