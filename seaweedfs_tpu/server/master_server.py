"""Master server: cluster control plane over gRPC + HTTP.

Behavioral match of the reference master
(weed/server/master_server.go, master_grpc_server*.go,
master_server_handlers*.go):

  * gRPC Heartbeat stream: volume servers push full-state inventories;
    the master registers them in the Topology, answers with the volume
    size limit, and unregisters the node when the stream breaks —
    liveness IS the stream (SURVEY §5 failure detection);
  * gRPC KeepConnected: filers/shells hold this open and receive
    vid→location deltas as volumes appear/disappear;
  * HTTP /dir/assign /dir/lookup /submit /vol/grow /vol/vacuum
    /col/delete /cluster/status /stats/health — the public control API
    (master_server.go:108-121);
  * automatic volume growth when an assign finds no writable volume
    (AutomaticGrowByType), allocating on rack-aware placed nodes via
    the volume servers' AllocateVolume RPC.

Single-master build: the raft leader seam is `self.is_leader` plus the
IdGenerator behind Topology.next_volume_id (SURVEY §7 "simplest
possible leader election first, raft-compatible interface later").
"""

from __future__ import annotations

import functools
import json
import queue
import random
import threading
import time
from concurrent import futures

import grpc

from seaweedfs_tpu import qos
from seaweedfs_tpu.cluster import health as health_mod
from seaweedfs_tpu.pb import master_pb2 as pb
from seaweedfs_tpu.util.httpd import (
    JSON_HDR as _JSON_HDR,
    FastHandler,
    WeedHTTPServer,
    fast_query,
)
from seaweedfs_tpu.pb import rpc, volume_pb2
from seaweedfs_tpu.sequence import MemorySequencer
from seaweedfs_tpu.storage.file_id import format_needle_id_cookie, parse_url_path
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.store import EcShardInfo, ScrubStatInfo, VolumeInfo
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.topology import Topology
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.topology.volume_growth import (
    find_empty_slots_for_one_volume,
    find_volume_count,
)


@functools.lru_cache(maxsize=256)
def _canonical_rp(s: str) -> str:
    return str(ReplicaPlacement.parse(s))


@functools.lru_cache(maxsize=256)
def _canonical_ttl(s: str) -> str:
    return str(TTL.parse(s))


def _vol_info_from_pb(v: pb.VolumeStat) -> VolumeInfo:
    return VolumeInfo(
        id=v.id,
        size=v.size,
        collection=v.collection,
        file_count=v.file_count,
        delete_count=v.delete_count,
        deleted_byte_count=v.deleted_byte_count,
        read_only=v.read_only,
        replica_placement=v.replica_placement,
        version=v.version,
        ttl=v.ttl,
    )


# /submit buffers its body in master memory; cap it (see _submit)
SUBMIT_MAX_BYTES = 256 * 1024 * 1024


class MasterServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 9333,
        volume_size_limit_mb: int = 30 * 1024,
        default_replication: str = "000",
        garbage_threshold: float = 0.3,
        guard=None,
        peers: str | list | None = None,
        raft_dir: str | None = None,
        vacuum_interval: float = 15 * 60.0,
        node_timeout: float = 30.0,
        metrics_address: str = "",
        metrics_interval_sec: int = 15,
        sequencer=None,
        repair_interval: float = 0.0,
        repair_concurrency: int = 2,
        repair_grace: float = 30.0,
        telemetry_interval: float = 0.0,
        telemetry_kwargs: dict | None = None,
        tier_interval: float = 0.0,
        tier_kwargs: dict | None = None,
        assign_policy: str = "p2c",
    ):
        # QoS plane (docs/QOS.md): "p2c" = queue-depth-aware
        # power-of-two-choices over writable volumes; "random" keeps
        # the pre-QoS pure-random pick (-assignPolicy random; WEED_QOS=0
        # forces it wholesale)
        self.assign_policy = assign_policy
        self.host = host
        self.port = port
        self.grpc_port = port + 10000  # reference convention: http port + 10000
        self.topology = Topology(volume_size_limit_mb * 1024 * 1024)
        # sequencer: injected (e.g. EtcdSequencer for external-KV
        # coordination), else durable file-backed when the master has a
        # meta directory (etcd_sequencer.go role), else in-memory
        if sequencer is not None:
            self.sequencer = sequencer
        elif raft_dir:
            import os as _os

            from seaweedfs_tpu.sequence import FileSequencer

            _os.makedirs(raft_dir, exist_ok=True)
            self.sequencer = FileSequencer(
                _os.path.join(raft_dir, f"sequencer-{port}.txt")
            )
        else:
            self.sequencer = MemorySequencer()
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.guard = guard  # security.Guard; assign responses carry a jwt
        from seaweedfs_tpu.stats import DurationCounter

        self.request_counter = DurationCounter()  # /stats/counter rolling UI
        # HA: peers (incl. self) => compact raft replicates MaxVolumeId
        # and elects the write coordinator (reference raft_server.go)
        self._raft = None
        peer_list = (
            [p.strip() for p in peers.split(",") if p.strip()]
            if isinstance(peers, str)
            else list(peers or [])
        )
        if peer_list:
            if not raft_dir:
                # without persisted term/vote a restarted master could
                # double-vote in a term it already voted in → split brain
                raise ValueError("peers requires raft_dir (persistent raft state)")
            from seaweedfs_tpu.cluster.raft import RaftNode

            self._raft = RaftNode(
                f"{host}:{port}",
                peer_list,
                self._apply_cluster_command,
                data_dir=raft_dir,
            )
        self._vid_alloc_lock = threading.Lock()
        self._grow_lock = threading.Lock()
        self._vacuum_sweep_lock = threading.Lock()
        # leader-only periodic garbage-ratio vacuum sweep
        # (master_server.go:126 StartRefreshWritableVolumes); 0 disables
        self.vacuum_interval = vacuum_interval
        # liveness: unregister nodes silent for this long even if their
        # heartbeat STREAM never tore down (frozen process, half-open
        # TCP) — stream teardown alone leaves the master routing writes
        # to a dead node until kernel keepalive fires; 0 disables
        self.node_timeout = node_timeout
        # serializes node-membership transitions: the sweep's multi-step
        # unregister vs a Heartbeat handler's check-register-sync
        # sequence (never held across a yield)
        self._node_lock = threading.Lock()
        self._stop_event = threading.Event()
        # pushed down to volume servers in HeartbeatResponse
        # (master_grpc_server.go:80-84)
        self.metrics_address = metrics_address
        self.metrics_interval_sec = metrics_interval_sec
        # scrub plane: the automatic repair scheduler (scrub/repair.py).
        # repair_interval <= 0 leaves repair manual (ec.rebuild /
        # volume.fix.replication in the shell); the `weed` CLI enables
        # it by default — tests and embedders opt in explicitly because
        # automatic rebuilds mid-admin-operation are a real behavior
        # change.
        self.repair = None
        if repair_interval > 0:
            from seaweedfs_tpu.scrub import RepairScheduler

            self.repair = RepairScheduler(
                self,
                interval=repair_interval,
                concurrency=repair_concurrency,
                grace=repair_grace,
            )
        self._clients: dict[int, queue.Queue] = {}
        self._clients_seq = 0
        self._clients_lock = threading.Lock()
        self._grpc_server: grpc.Server | None = None
        self._http_server: WeedHTTPServer | None = None
        # telemetry plane (docs/TELEMETRY.md): leader-only /metrics
        # scraper + ring TSDB + alert rules. telemetry_interval <= 0
        # leaves the plane off — the `weed` CLI enables it by default;
        # tests and embedders opt in (a background scraper hitting
        # every node changes observable traffic).
        self.telemetry = None
        if telemetry_interval > 0:
            from seaweedfs_tpu.telemetry import ClusterCollector

            self.telemetry = ClusterCollector(
                self, interval=telemetry_interval, **(telemetry_kwargs or {})
            )
            self._wire_capsules()
        # tiering plane (docs/TIERING.md): leader-only lifecycle
        # scheduler driving tier-out/tier-in moves at the shard
        # holders. tier_interval <= 0 leaves tiering manual (tier.move
        # in the shell) — same opt-in contract as repair/telemetry.
        self.tier = None
        if tier_interval > 0:
            from seaweedfs_tpu.tier import TierScheduler

            self.tier = TierScheduler(
                self, interval=tier_interval, **(tier_kwargs or {})
            )
        # gateway registration (/cluster/register): filer/S3/WebDAV
        # announce themselves here so the collector can scrape them —
        # they have no heartbeat stream to be discovered from
        self._gateways: dict[str, dict] = {}
        self._gateways_lock = threading.Lock()
        # weedguard health plane (docs/HEALTH.md): per-node phi-accrual
        # suspicion + error EWMAs + lame-duck/drain flags, scored from
        # heartbeats. Always on (cheap); WEED_HEALTH=0 makes every
        # verdict read healthy, restoring pre-health behavior wholesale.
        self.health = health_mod.HealthPlane()

    def _wire_capsules(self) -> None:
        """weedscope (docs/TELEMETRY.md): leader-side capsule wiring.
        Firing alerts trigger a local capture plus remote captures on
        every implicated node, and the master's capsules grow the
        leader-only sections: the relevant TSDB window, the alert/SLO
        verdicts, and the health-plane snapshot."""
        from seaweedfs_tpu.telemetry import capsule
        from seaweedfs_tpu.trace import blackbox

        tel = self.telemetry

        def peers_for(alert_row: dict) -> list[str]:
            target = alert_row.get("Target", "")
            if ":" in target:  # node-scoped alert: that node is enough
                return [target]
            # cluster-scoped (SLO objective, repair depth): everyone
            # currently serving is implicated — fan the capture out
            return tel.up_targets()

        tel.alerts.on_fire = capsule.CaptureCoordinator(
            node=f"{self.host}:{self.port}",
            peers_fn=peers_for,
            enabled_fn=blackbox.enabled,
        )
        capsule.add_provider("tsdb", tel.window_payload)
        capsule.add_provider(
            "cluster",
            lambda: {
                "Alerts": tel.alerts.payload(),
                "SLO": tel.slo_payload(),
                "Health": tel.health_payload(),
            },
        )

    # gateways silent for this long stop being offered to the collector
    # (its own sticky-target window keeps their staleness alert alive
    # long before this prune runs)
    GATEWAY_TTL = 3600.0

    def register_gateway(self, kind: str, addr: str) -> None:
        with self._gateways_lock:
            self._gateways[addr] = {"kind": kind, "last_seen": time.time()}

    def gateway_registrations(self) -> dict[str, dict]:
        now = time.time()
        with self._gateways_lock:
            for addr in [
                a for a, row in self._gateways.items()
                if now - row["last_seen"] > self.GATEWAY_TTL
            ]:
                del self._gateways[addr]
            return {a: dict(r) for a, r in self._gateways.items()}

    @property
    def is_leader(self) -> bool:
        return self._raft.is_leader if self._raft else True

    def leader_address(self) -> str:
        hint = self._raft.leader() if self._raft else ""
        return hint or f"{self.host}:{self.port}"

    def _apply_cluster_command(self, cmd: dict) -> None:
        """Raft state machine (cluster_commands.go MaxVolumeIdCommand)."""
        if cmd.get("name") == "MaxVolumeId":
            self.topology.id_gen.adjust_if_larger(int(cmd["maxVolumeId"]))

    def _next_volume_id(self) -> int:
        """Allocate the next volume id; with raft, the allocation is
        replicated to a majority before use (topology.go NextVolumeId →
        raft Do(MaxVolumeIdCommand))."""
        if self._raft is None:
            return self.topology.next_volume_id()
        with self._vid_alloc_lock:
            # a freshly elected leader may hold committed-but-unapplied
            # MaxVolumeId entries from the prior term; drain them before
            # peeking or the next vid could collide with an existing one
            self._raft.barrier()
            vid = self.topology.id_gen.peek() + 1
            self._raft.propose({"name": "MaxVolumeId", "maxVolumeId": vid})
            return vid

    # ------------------------------------------------------------------
    # location broadcast (master_grpc_server.go KeepConnected)
    def _broadcast(self, url: str, public_url: str, new_vids: list[int], deleted_vids: list[int]) -> None:
        msg = pb.VolumeLocationDelta(
            location=pb.VolumeLocation(
                url=url, public_url=public_url, new_vids=new_vids, deleted_vids=deleted_vids
            )
        )
        with self._clients_lock:
            for q in self._clients.values():
                q.put(msg)

    # ------------------------------------------------------------------
    # gRPC servicer methods (bound via rpc.servicer_handler)
    def Heartbeat(self, request_iterator, context):
        dn = None
        stream_token = object()
        was_detached = False
        need_full = False  # ask the node to resend its full inventory
        try:
            for req in request_iterator:
                if not self.is_leader:
                    # redirect before registering: a follower must not
                    # ingest the node (clients on KeepConnected would
                    # see the volume map flap on every redirect)
                    yield pb.HeartbeatResponse(
                        volume_size_limit=self.topology.volume_size_limit,
                        leader=self.leader_address(),
                    )
                    return
                # the whole check-register-sync sequence runs under the
                # node lock so the liveness sweep can't detach the node
                # between the parent check and the volume sync (which
                # would re-register volumes onto an orphan the sweep
                # never sees again); the lock is NOT held across yield
                with self._node_lock:
                    if dn is not None and dn.parent is None:
                        # the liveness sweep declared this node dead
                        # while the stream stayed open (frozen process
                        # that woke up): register afresh. Volume state
                        # repopulates on the node's next full beat
                        # (every _FULL_HEARTBEAT_EVERY cycles); until
                        # then the master routes nothing to it.
                        dn = None
                        was_detached = True
                    if dn is None:
                        dn = self.topology.register_data_node(
                            ip=req.ip,
                            port=req.port,
                            public_url=req.public_url,
                            data_center=req.data_center or "DefaultDataCenter",
                            rack=req.rack or "DefaultRack",
                            max_volumes=req.max_volume_count or 7,
                        )
                        existing = getattr(dn, "stream_token", None)
                        if (
                            was_detached
                            and existing is not None
                            and existing is not stream_token
                        ):
                            # we were swept AND another live stream has
                            # since registered this node: ours is the
                            # obsolete one — end it without stealing
                            # ownership (the finally's token check then
                            # leaves the live node alone)
                            return
                        # a fresh reconnect takes ownership; the stale
                        # stream's teardown must not unregister the
                        # live node
                        dn.stream_token = stream_token
                        if was_detached:
                            # we registered a blank node mid-stream: the
                            # node's delta beats are useless until it
                            # resends the full inventory
                            need_full = True
                    dn.last_seen = time.time()
                    # QoS plane: live load for queue-depth-aware
                    # assignment (pick_for_write power-of-two-choices)
                    dn.in_flight = req.in_flight_requests
                    dn.write_queue_depth = req.write_queue_depth
                    # health plane (docs/HEALTH.md): beat arrival time
                    # feeds the phi-accrual detector, the counters feed
                    # the error EWMA, and the node's own lame-duck /
                    # draining flags land here
                    self.health.observe_heartbeat(dn.url, req)
                    self.sequencer.set_max(req.max_file_key)
                    if req.volumes or req.has_no_volumes:
                        new, deleted = self.topology.sync_volumes(
                            dn, [_vol_info_from_pb(v) for v in req.volumes]
                        )
                        if new or deleted:
                            self._broadcast(
                                dn.url,
                                dn.public_url,
                                [v.id for v in new],
                                [v.id for v in deleted],
                            )
                    elif req.new_volumes or req.deleted_volumes:
                        # delta beat: O(changes) registration. Stat
                        # changes to already-registered volumes update
                        # layouts but must not spam KeepConnected
                        # clients as "new"
                        new = [_vol_info_from_pb(v) for v in req.new_volumes]
                        deleted = [
                            _vol_info_from_pb(v) for v in req.deleted_volumes
                        ]
                        truly_new = [
                            v.id for v in new if v.id not in dn.volumes
                        ]
                        self.topology.delta_sync_volumes(dn, new, deleted)
                        if truly_new or deleted:
                            self._broadcast(
                                dn.url,
                                dn.public_url,
                                truly_new,
                                [v.id for v in deleted],
                            )
                    if req.ec_shards or req.has_no_ec_shards:
                        self.topology.sync_ec_shards(
                            dn,
                            [
                                EcShardInfo(s.id, s.collection, s.ec_index_bits)
                                for s in req.ec_shards
                            ],
                        )
                    # scrub plane: every beat carries the node's full
                    # scrub-health snapshot (quarantines arrive on a
                    # FORCED delta beat, so damage lands here within
                    # one heartbeat RTT of detection)
                    def _damage_sig(stats):
                        # only the damage-relevant fields: scanned_bytes
                        # advances every beat during a sweep, so a
                        # whole-row comparison would re-trigger the
                        # scheduler once per heartbeat
                        return {
                            (k, s.corruptions_found, s.quarantined_shard_bits)
                            for k, s in stats.items()
                            if s.corruptions_found or s.quarantined_shard_bits
                        }

                    prev_sig = _damage_sig(dn.scrub_stats)
                    self.topology.sync_scrub_stats(
                        dn,
                        [
                            ScrubStatInfo(
                                volume_id=s.volume_id,
                                is_ec=s.is_ec,
                                last_sweep_unix=s.last_sweep_unix,
                                scanned_bytes=s.scanned_bytes,
                                corruptions_found=s.corruptions_found,
                                quarantined_shard_bits=s.quarantined_shard_bits,
                                last_error=s.last_error,
                            )
                            for s in req.scrub_stats
                        ],
                    )
                    new_sig = _damage_sig(dn.scrub_stats)
                    # disk-health signal for the health plane: this
                    # node's scrub rows currently report damage
                    self.health.observe_scrub(dn.url, bool(new_sig))
                    if (
                        self.repair is not None
                        and new_sig
                        and new_sig != prev_sig
                    ):
                        # a NEW damage report (not the same rows riding
                        # every beat): scan now, don't wait the tick
                        self.repair.trigger()
                    if need_full and (req.volumes or req.has_no_volumes):
                        need_full = False  # full inventory received
                yield pb.HeartbeatResponse(
                    volume_size_limit=self.topology.volume_size_limit,
                    leader=self.leader_address(),
                    metrics_address=self.metrics_address,
                    metrics_interval_seconds=self.metrics_interval_sec,
                    request_full_heartbeat=need_full,
                )
        finally:
            with self._node_lock:
                if (
                    dn is not None
                    and getattr(dn, "stream_token", None) is stream_token
                ):
                    vids = list(dn.volumes)
                    self.topology.unregister_data_node(dn)
                    self.health.note_dead(dn.url)
                    if vids:
                        self._broadcast(dn.url, dn.public_url, [], vids)

    def KeepConnected(self, request_iterator, context):
        with self._clients_lock:
            self._clients_seq += 1
            cid = self._clients_seq
            q: queue.Queue = queue.Queue()
            self._clients[cid] = q
        try:
            # ack first so clients learn the leader even on an empty
            # cluster (reference sends leader redirects the same way)
            q.put(pb.VolumeLocationDelta(leader=self.leader_address()))
            # seed: full current map
            for dn in self.topology.data_nodes():
                vids = list(dn.volumes) + list(dn.ec_shards)
                if vids:
                    q.put(
                        pb.VolumeLocationDelta(
                            location=pb.VolumeLocation(
                                url=dn.url, public_url=dn.public_url, new_vids=vids
                            )
                        )
                    )
            next(iter(request_iterator))  # hello
            while context.is_active():
                try:
                    yield q.get(timeout=1.0)
                except queue.Empty:
                    continue
        except StopIteration:
            pass
        finally:
            with self._clients_lock:
                self._clients.pop(cid, None)

    def Assign(self, req: pb.AssignRequest, context) -> pb.AssignResponse:
        try:
            result = self.assign(
                count=req.count or 1,
                replication=req.replication,
                collection=req.collection,
                ttl=req.ttl,
                data_center=req.data_center,
            )
        except Exception as e:  # noqa: BLE001 - error travels in-band
            return pb.AssignResponse(error=str(e))
        return pb.AssignResponse(
            fid=result["fid"],
            url=result["url"],
            public_url=result["publicUrl"],
            count=result["count"],
            auth=result.get("auth", ""),
        )

    def _proxy_to_leader_stub(self, wait: float = 3.0):
        """Stub on the leader, or None when this master IS the leader
        (master_server.go:151 proxyToLeader: followers hold no
        topology — volume servers heartbeat only the leader — so reads
        must be answered there). Waits out brief leaderless election
        windows instead of failing instantly."""
        deadline = time.time() + wait
        while True:
            leader = self.leader_address()
            known = self._raft is None or self._raft.leader()
            if leader == f"{self.host}:{self.port}" and known:
                return None  # we are the leader
            if leader != f"{self.host}:{self.port}" and known:
                ch = grpc.insecure_channel(rpc.grpc_address(leader))
                return ch, rpc.master_stub(ch)
            if time.time() >= deadline:
                return "unknown"
            # weedlint: ignore[hot-loop-sleep] — bounded 3 s leader-election wait; failing instantly would 503 every read during each election window
            time.sleep(0.05)

    def _proxy_or_abort(self, context, verb: str, req, timeout: float):
        """Follower-side leader proxy for read verbs: returns the
        leader's response, None when THIS master is the leader (caller
        answers locally), or aborts UNAVAILABLE — an empty local
        answer from a follower would poison clients silently."""
        proxied = self._proxy_to_leader_stub()
        if proxied == "unknown":
            context.abort(grpc.StatusCode.UNAVAILABLE, "no leader elected yet")
        if proxied is None:
            return None
        ch, stub = proxied
        try:
            return getattr(stub, verb)(req, timeout=timeout)
        except grpc.RpcError:
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "leader unreachable from this follower",
            )
        finally:
            ch.close()

    def LookupVolume(self, req: pb.LookupVolumeRequest, context) -> pb.LookupVolumeResponse:
        if not self.is_leader:
            resp = self._proxy_or_abort(context, "LookupVolume", req, 10)
            if resp is not None:
                return resp
        out = pb.LookupVolumeResponse()
        for vid_str in req.vids:
            entry = out.vid_locations.add(vid=vid_str)
            try:
                vid = int(vid_str.split(",")[0])
            except ValueError:
                entry.error = f"unknown volume id {vid_str}"
                continue
            nodes = self.topology.lookup(req.collection, vid)
            if not nodes:
                entry.error = f"volume id {vid} not found"
                continue
            # health plane (docs/HEALTH.md): suspects ordered last AND
            # marked, so every client demotes them cluster-wide (the
            # per-process circuit breaker only learns from its own
            # timeouts) and the hedge driver fires eagerly
            for dn in self.health.order_nodes(nodes):
                entry.locations.add(
                    url=dn.url,
                    public_url=dn.public_url,
                    suspect=self.health.suspect(dn.url),
                )
        return out

    def LookupEcVolume(self, req: pb.LookupEcVolumeRequest, context) -> pb.LookupEcVolumeResponse:
        if not self.is_leader:
            resp = self._proxy_or_abort(context, "LookupEcVolume", req, 10)
            if resp is not None:
                return resp
        out = pb.LookupEcVolumeResponse(volume_id=req.volume_id)
        locs = self.topology.lookup_ec_shards(req.volume_id)
        if locs is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"ec volume {req.volume_id} not found")
        for shard_id, nodes in enumerate(locs.locations):
            if not nodes:
                continue
            entry = out.shard_id_locations.add(shard_id=shard_id)
            for dn in nodes:
                entry.locations.add(url=dn.url, public_url=dn.public_url)
        return out

    def Statistics(self, req: pb.StatisticsRequest, context) -> pb.StatisticsResponse:
        if not self.is_leader:
            resp = self._proxy_or_abort(context, "Statistics", req, 10)
            if resp is not None:
                return resp
        total = used = files = 0
        for dn in self.topology.data_nodes():
            for v in dn.volumes.values():
                if req.collection and v.collection != req.collection:
                    continue
                used += v.size
                files += v.file_count
        total = self.topology.max_volume_count() * self.topology.volume_size_limit
        return pb.StatisticsResponse(total_size=total, used_size=used, file_count=files)

    def CollectionList(self, req, context) -> pb.CollectionListResponse:
        return pb.CollectionListResponse(collections=sorted(self.topology.collections()))

    def CollectionDelete(self, req: pb.CollectionDeleteRequest, context):
        if not self.is_leader:
            resp = self._proxy_or_abort(context, "CollectionDelete", req, 30)
            if resp is not None:
                return resp
        for dn in self.topology.data_nodes():
            try:
                with rpc.dial(self._node_grpc(dn)) as ch:
                    rpc.volume_stub(ch).DeleteCollection(
                        volume_pb2.DeleteCollectionRequest(collection=req.name)
                    )
            except grpc.RpcError:
                pass
        return pb.CollectionDeleteResponse()

    def VolumeList(self, req, context) -> pb.VolumeListResponse:
        return pb.VolumeListResponse(
            topology_json=json.dumps(self._topology_dump()),
            volume_size_limit_mb=self.topology.volume_size_limit // (1024 * 1024),
        )

    def GetMasterConfiguration(self, req, context):
        return pb.GetMasterConfigurationResponse()

    # ------------------------------------------------------------------
    # assignment (master_server_handlers.go:96 dirAssignHandler)
    def assign(
        self,
        count: int = 1,
        replication: str = "",
        collection: str = "",
        ttl: str = "",
        data_center: str = "",
    ) -> dict:
        if not self.is_leader:
            # proxy to the leader (master_server.go:151 proxyToLeader):
            # clients may talk to any master; only the leader assigns
            return self._proxy_assign(
                count, replication, collection, ttl, data_center
            )
        # normalize to the same canonical forms heartbeat registration
        # uses, so both paths land in the same layout (memoized: the
        # same handful of strings arrive on every assign)
        rp = _canonical_rp(replication or self.default_replication)
        ttl = _canonical_ttl(ttl)
        if not self.topology.has_writable_volume(collection, rp, ttl):
            if self.topology.free_space() <= 0:
                raise RuntimeError("no free volumes left")
            with self._grow_lock:
                if not self.topology.has_writable_volume(collection, rp, ttl):
                    self.grow_volumes(collection, rp, ttl, data_center=data_center)
        vid, _, nodes = self.topology.pick_for_write(
            collection, rp, ttl, count,
            data_center=data_center,
            policy=self.assign_policy if qos.enabled("assign") else "random",
            # health plane (docs/HEALTH.md): prefer volumes whose
            # replicas are all assignable — suspects/lame-ducks/
            # draining nodes stop receiving writes as soon as the
            # master suspects them, not when requests start timing out
            health=self.health,
        )
        file_key = self.sequencer.next_file_id(count)
        cookie = random.randrange(1 << 32)
        fid = f"{vid},{format_needle_id_cookie(file_key, cookie)}"
        dn = nodes[0]
        result = {
            "fid": fid,
            "url": dn.url,
            "publicUrl": dn.public_url,
            "count": count,
        }
        if self.guard is not None and self.guard.signing_key:
            # write token scoped to the assigned fid, handed to the
            # client the way the reference's assign response carries
            # `auth` (security.GenJwt on the master side)
            result["auth"] = self.guard.sign_write(fid)
        return result

    def _node_grpc(self, dn) -> str:
        return f"{dn.ip}:{dn.port + 10000}"

    def grow_volumes(
        self, collection: str, replication: str, ttl: str, data_center: str = "", target_count: int = 0
    ) -> int:
        """AutomaticGrowByType (volume_growth.go:63)."""
        rp = ReplicaPlacement.parse(replication)
        replication = str(rp)
        ttl = str(TTL.parse(ttl))
        target = target_count or find_volume_count(rp.copy_count)
        grown = 0
        for _ in range(target):
            try:
                servers = find_empty_slots_for_one_volume(
                    self.topology, rp, data_center=data_center
                )
            except ValueError:
                break
            vid = self._next_volume_id()
            ok = True
            for dn in servers:
                try:
                    with rpc.dial(self._node_grpc(dn)) as ch:
                        rpc.volume_stub(ch).AllocateVolume(
                            volume_pb2.AllocateVolumeRequest(
                                volume_id=vid,
                                collection=collection,
                                replication=replication,
                                ttl=ttl,
                            ),
                            timeout=5,
                        )
                except grpc.RpcError as e:
                    ok = False
                    break
            if ok:
                # register immediately (volume_growth.go grow() does the
                # same; the next heartbeat confirms)
                layout = self.topology.get_layout(collection, replication, ttl)
                for dn in servers:
                    info = VolumeInfo(
                        id=vid,
                        size=0,
                        collection=collection,
                        file_count=0,
                        delete_count=0,
                        deleted_byte_count=0,
                        read_only=False,
                        replica_placement=rp.to_byte(),
                        version=3,
                        ttl=0,
                    )
                    dn.volumes[vid] = info
                    layout.register_volume(info, dn)
                grown += 1
        if grown == 0:
            raise RuntimeError("failed to grow any volume")
        return grown

    def _topology_dump(self) -> dict:
        return self.topology.to_map()

    # ------------------------------------------------------------------
    # HTTP (master_server_handlers.go)
    def _http_handler_class(self):
        server = self

        class Handler(FastHandler):
            def _html(self, body: str, status=200):
                self.fast_reply(
                    status,
                    body.encode(),
                    {"Content-Type": "text/html; charset=utf-8"},
                )

            def _json(self, obj, status=200):
                self.fast_reply(status, json.dumps(obj).encode(), _JSON_HDR)

            def do_GET(self):
                server.request_counter.add()
                path, _, qs = self.path.partition("?")
                q = fast_query(qs)
                if self.command == "POST" and path != "/submit":
                    # keep-alive hygiene: drain any request body now —
                    # an unread body would be parsed as the next
                    # request line on this connection (/submit reads
                    # its own body in _submit)
                    try:
                        n = int(self.headers.get("Content-Length", "0"))
                    except ValueError:
                        n = 0
                    if n > 64 << 20:
                        # nothing but /submit legitimately posts a large
                        # body here; don't buffer-drain unbounded data
                        self.close_connection = True
                        return self._json({"error": "request body too large"}, 413)
                    while n > 0:
                        chunk = self.rfile.read(min(n, 1 << 20))
                        if not chunk:
                            break
                        n -= len(chunk)
                if path == "/dir/assign":
                    return self._assign(q)
                if path == "/dir/lookup":
                    return self._lookup(q)
                if path in ("/", "/ui/index.html"):
                    return self._html(server._render_master_ui())
                if path == "/cluster/status":
                    return self._json(
                        {
                            "IsLeader": server.is_leader,
                            "Leader": server.leader_address(),
                            "Peers": server._raft.peers if server._raft else [],
                        }
                    )
                if path == "/dir/status":
                    return self._json({"Topology": server._topology_dump()})
                if path == "/stats/health":
                    return self._json({"ok": True})
                if path == "/cluster/register":
                    # gateway announce (telemetry/announce.py): record
                    # on the leader so the collector that scrapes is
                    # the one that knows the gateway exists. addr must
                    # LOOK like host:port — the collector will dial
                    # http://<addr>/metrics every cycle, so a free-form
                    # string would turn the leader into an arbitrary-
                    # URL fetcher (and a permanent bogus-alert source)
                    kind = q.get("kind", "")
                    addr = q.get("addr", "")
                    host, _, port_s = addr.rpartition(":")
                    if (
                        not kind
                        or len(kind) > 32
                        or not host
                        or len(addr) > 256
                        or not port_s.isdigit()
                        or not int(port_s) < 65536
                        or any(c in host for c in "/?#@ \t")
                    ):
                        return self._json(
                            {"error": "kind and addr (host:port) required"},
                            400,
                        )
                    if not server.is_leader:
                        return self._proxy_http_to_leader()
                    server.register_gateway(kind, addr)
                    return self._json({"ok": True})
                if path == "/node/drain":
                    # weedguard (docs/HEALTH.md): operator drain intent
                    # for one volume server — excluded from assignment
                    # immediately, and the RepairScheduler moves its
                    # volumes/EC shards off (the node.drain shell
                    # command drives + polls this). ?stop=1 cancels;
                    # ?status=1 is the READ-ONLY poll form (no
                    # re-marking, no scheduler wake — the shell's
                    # -wait loop would otherwise re-fire the mutation
                    # twice a second).
                    node = q.get("node", "")
                    if not node or ":" not in node:
                        return self._json(
                            {"error": "node=host:port required"}, 400
                        )
                    if not server.is_leader:
                        return self._proxy_http_to_leader()
                    stop = q.get("stop", "") in ("1", "true")
                    if q.get("status", "") not in ("1", "true"):
                        server.health.request_drain(node, stop=stop)
                        if server.repair is not None and not stop:
                            server.repair.trigger()
                    dn = next(
                        (
                            d
                            for d in server.topology.data_nodes()
                            if d.url == node
                        ),
                        None,
                    )
                    return self._json(
                        {
                            "node": node,
                            "draining": not stop,
                            "registered": dn is not None,
                            "volumes": len(dn.volumes) if dn else 0,
                            "ecShards": dn.ec_shard_count() if dn else 0,
                            "repairScheduler": server.repair is not None,
                        }
                    )
                if path in (
                    "/cluster/health",
                    "/cluster/alerts",
                    "/cluster/top",
                    "/cluster/slo",
                ):
                    if not server.is_leader:
                        # followers hold no topology and run no
                        # collector cycles (their local collector may
                        # even be disabled); the leader's aggregates
                        # are the cluster's — proxy BEFORE the
                        # disabled check so a follower never answers
                        # "Disabled" for a cluster whose leader is
                        # collecting fine
                        return self._proxy_http_to_leader()
                    if path == "/cluster/health":
                        # weedguard (docs/HEALTH.md): per-node health
                        # scores/states ride this surface even with the
                        # telemetry collector off — the health plane
                        # lives on heartbeats alone
                        payload = {"NodeHealth": server.health.payload()}
                        if server.telemetry is None:
                            payload["Disabled"] = True
                            payload["error"] = (
                                "telemetry collector disabled "
                                "on this master (-telemetryInterval 0)"
                            )
                        else:
                            payload.update(
                                server.telemetry.health_payload()
                            )
                        return self._json(payload)
                    if server.telemetry is None:
                        return self._json(
                            {
                                "Disabled": True,
                                "error": "telemetry collector disabled "
                                "on this master (-telemetryInterval 0)",
                            }
                        )
                    if path == "/cluster/alerts":
                        return self._json(server.telemetry.alerts.payload())
                    if path == "/cluster/slo":
                        # weedscope (docs/TELEMETRY.md): per-objective
                        # burn rates, budget remaining, and the soak
                        # scorecard — the cluster.slo shell surface
                        return self._json(server.telemetry.slo_payload())
                    try:
                        n = int(q.get("n", "10"))
                    except ValueError:
                        n = 10
                    return self._json(server.telemetry.top_payload(n))
                if path == "/repair/queue":
                    # scrub plane operator surface (repair.queue shell
                    # command): scheduler config, tracked damage with
                    # backoff state, and recent repair history
                    if server.repair is None:
                        return self._json(
                            {"Disabled": True, "Scrub": server.topology.scrub_summary()}
                        )
                    snap = server.repair.queue_snapshot()
                    snap["Scrub"] = server.topology.scrub_summary()
                    return self._json(snap)
                if path == "/cluster/tier":
                    # tiering plane operator surface (tier.status shell
                    # command): scheduler rules, in-flight moves, and
                    # recent move history (docs/TIERING.md)
                    if server.tier is None:
                        return self._json(
                            {
                                "Disabled": True,
                                "error": "tier scheduler disabled on "
                                "this master (-tierInterval 0)",
                            }
                        )
                    return self._json(server.tier.status_snapshot())
                if path == "/stats/counter":
                    return self._json(server.request_counter.snapshot())
                if path == "/stats/memory":
                    import resource

                    ru = resource.getrusage(resource.RUSAGE_SELF)
                    return self._json({"maxrss_kb": ru.ru_maxrss})
                if path == "/metrics":
                    from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY

                    body = DEFAULT_REGISTRY.render_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return self.wfile.write(body)
                if path == "/vol/grow":
                    try:
                        count = server.grow_volumes(
                            q.get("collection", ""),
                            q.get("replication", server.default_replication),
                            q.get("ttl", ""),
                            data_center=q.get("dataCenter", ""),
                            target_count=int(q.get("count", "0")),
                        )
                        return self._json({"count": count})
                    except Exception as e:  # noqa: BLE001
                        return self._json({"error": str(e)}, 500)
                if path == "/col/delete":
                    return self._json({"error": "use gRPC CollectionDelete"}, 400)
                if path == "/submit":
                    return self._submit(q)
                if path == "/vol/vacuum":
                    return self._vol_vacuum(q)
                if path == "/vol/status":
                    return self._json(
                        {
                            "Version": "seaweedfs_tpu",
                            "Volumes": server.topology.to_volume_map(),
                        }
                    )
                # fallthrough: GET /<fid> on the master 301s to a
                # volume server holding it (master_server.go:121
                # redirectHandler) — the curl-the-master convenience
                redirected = self._redirect_fid(path, q)
                if redirected:
                    return
                self._json({"error": f"unknown path {path}"}, 404)

            def _redirect_fid(self, path, q) -> bool:
                vid_str, fid_str, _fn, _ext, _vo = parse_url_path(path)
                # isascii guard: str.isdigit() accepts unicode digits
                # that int() then rejects
                if not (vid_str.isascii() and vid_str.isdigit()) or not fid_str:
                    return False
                nodes = server.topology.lookup(
                    q.get("collection", ""), int(vid_str)
                )
                if not nodes:
                    self._json(
                        {"error": f"volume id {vid_str} not found"}, 404
                    )
                    return True
                # redirect readers at a non-suspect replica when one
                # exists (health plane, docs/HEALTH.md)
                healthy = [
                    dn for dn in nodes if not server.health.suspect(dn.url)
                ]
                dn = random.choice(healthy or nodes)
                target = f"http://{dn.public_url}{self.path}"
                self.fast_reply(301, b"", {"Location": target})
                return True

            do_POST = do_GET

            def _submit(self, q):
                """Assign + proxy upload in one call — the curl
                one-liner path (master_server.go:116 /submit →
                submitForClientHandler). Routes through the client
                submit op against this master, so auto-chunking
                (?maxMB=) and assign's leader proxying both apply."""
                from seaweedfs_tpu.client.operation import submit_file
                from seaweedfs_tpu.util.multipart import (
                    MalformedUpload,
                    parse_upload,
                )

                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    max_mb = int(q.get("maxMB", "0") or "0")
                except ValueError:
                    return self._json(
                        {"error": "maxMB / Content-Length must be integers"},
                        400,
                    )
                if length > SUBMIT_MAX_BYTES:
                    # /submit buffers the upload in master memory before
                    # assign+proxy (the convenience path); bound it so a
                    # huge or malicious body can't OOM the control
                    # plane — bulk ingest belongs on the volume/filer
                    # data planes
                    return self._json(
                        {
                            "error": f"/submit caps uploads at "
                            f"{SUBMIT_MAX_BYTES >> 20} MiB; upload via "
                            "assign + volume POST (or the filer) instead"
                        },
                        413,
                    )
                body = self.rfile.read(length)
                try:
                    part = parse_upload(
                        body, self.headers.get("Content-Type", "")
                    )
                except MalformedUpload as e:
                    return self._json({"error": str(e)}, 400)
                try:
                    res = submit_file(
                        f"{server.host}:{server.port}",
                        q.get("filename", "") or part.filename,
                        part.data,
                        replication=q.get("replication", ""),
                        collection=q.get("collection", ""),
                        ttl=q.get("ttl", ""),
                        mime=part.mime,
                        max_mb=max_mb,
                    )
                except Exception as e:  # noqa: BLE001
                    return self._json({"error": str(e)}, 500)
                if res.error:
                    return self._json({"error": res.error}, 500)
                self._json(
                    {
                        "fileName": res.file_name,
                        "fid": res.fid,
                        "fileUrl": res.file_url,
                        "size": res.size,
                    }
                )

            def _vol_vacuum(self, q):
                """Force one garbage-ratio vacuum sweep now
                (master_server.go:117 /vol/vacuum); optional
                ?garbageThreshold= overrides the configured ratio.
                Followers proxy to the leader, who owns the topology."""
                if not server.is_leader:
                    return self._proxy_http_to_leader()
                try:
                    threshold = (
                        float(q["garbageThreshold"])
                        if "garbageThreshold" in q
                        else None
                    )
                except ValueError:
                    return self._json(
                        {"error": "garbageThreshold must be a float"}, 400
                    )
                try:
                    count = server._vacuum_once(threshold=threshold)
                except Exception as e:  # noqa: BLE001
                    return self._json({"error": str(e)}, 500)
                self._json(
                    {"vacuumed": count, "Topology": server._topology_dump()}
                )

            def _proxy_http_to_leader(self):
                from seaweedfs_tpu.client.operation import http_call

                leader = server.leader_address()
                if not leader or leader == f"{server.host}:{server.port}":
                    return self._json({"error": "no leader to proxy to"}, 503)
                try:
                    status, headers, body = http_call(
                        "GET", f"{leader}{self.path}", timeout=630
                    )
                except Exception as e:  # noqa: BLE001
                    return self._json({"error": f"leader proxy: {e}"}, 502)
                self.send_response(status)
                self.send_header(
                    "Content-Type",
                    headers.get("Content-Type", "application/json"),
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _assign(self, q):
                try:
                    result = server.assign(
                        count=int(q.get("count", "1")),
                        replication=q.get("replication", ""),
                        collection=q.get("collection", ""),
                        ttl=q.get("ttl", ""),
                        data_center=q.get("dataCenter", ""),
                    )
                    self._json(result)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": str(e)}, 500)

            def _lookup(self, q):
                vid_str = q.get("volumeId", "")
                try:
                    vid = int(vid_str.split(",")[0])
                except ValueError:
                    return self._json({"error": f"unknown volumeId {vid_str}"}, 400)
                nodes = server.topology.lookup(q.get("collection", ""), vid)
                if not nodes:
                    return self._json(
                        {"volumeId": vid_str, "error": "volume id not found"}, 404
                    )
                # suspects last + marked (health plane, docs/HEALTH.md)
                self._json(
                    {
                        "volumeId": vid_str,
                        "locations": [
                            {
                                "url": dn.url,
                                "publicUrl": dn.public_url,
                                "suspect": server.health.suspect(dn.url),
                            }
                            for dn in server.health.order_nodes(nodes)
                        ],
                    }
                )

        return Handler

    # ------------------------------------------------------------------
    def _proxy_assign(
        self, count, replication, collection, ttl, data_center
    ) -> dict:
        leader = self.leader_address()
        if leader == f"{self.host}:{self.port}":
            raise RuntimeError("no leader elected yet")
        with rpc.dial(rpc.grpc_address(leader)) as ch:
            resp = rpc.master_stub(ch).Assign(
                pb.AssignRequest(
                    count=count,
                    replication=replication,
                    collection=collection,
                    ttl=ttl,
                    data_center=data_center,
                ),
                timeout=10,
            )
        if resp.error:
            raise RuntimeError(resp.error)
        return {
            "fid": resp.fid,
            "url": resp.url,
            "publicUrl": resp.public_url,
            "count": resp.count,
            **({"auth": resp.auth} if resp.auth else {}),
        }


    # ------------------------------------------------------------------
    # status UI (server/master_ui/templates.go role)
    def _render_master_ui(self) -> str:
        import html as _html

        rows = []
        for dn in self.topology.data_nodes():
            rack = dn.parent.id if dn.parent is not None else ""
            dc = (
                dn.parent.parent.id
                if dn.parent is not None and dn.parent.parent is not None
                else ""
            )
            rows.append(
                f"<tr><td>{_html.escape(dc)}</td><td>{_html.escape(rack)}</td>"
                f"<td><a href='http://{_html.escape(dn.public_url)}/ui/index.html'>"
                f"{_html.escape(dn.url)}</a></td>"
                f"<td>{len(dn.volumes)}</td><td>{dn.max_volume_count()}</td>"
                f"<td>{len(dn.ec_shards)}</td></tr>"
            )
        role = "leader" if self.is_leader else "follower"
        from seaweedfs_tpu.util.status_ui import status_page

        return status_page(
            "SeaweedFS-TPU Master",
            f"Master {self.host}:{self.port}",
            f"role: <b>{role}</b> &middot; leader: {self.leader_address()}"
            f" &middot; max volume id: {self.topology.id_gen.peek()}",
            ["DataCenter", "Rack", "Node", "Volumes", "Max", "EC shards"],
            "".join(rows),
            ["/dir/status", "/cluster/status", "/metrics"],
        )

    # ------------------------------------------------------------------
    # leader vacuum loop (topology_vacuum.go:16-160 via
    # topology_event_handling.go StartRefreshWritableVolumes)
    def _vacuum_once(self, threshold: float | None = None) -> int:
        """One garbage-ratio sweep: replica-consistent check → compact
        all replicas → commit all (cleanup on failure). Returns the
        number of vacuumed volumes. `threshold` overrides the
        configured garbage ratio for this sweep (the /vol/vacuum
        ?garbageThreshold= path)."""
        if threshold is None:
            threshold = self.garbage_threshold
        # serialize sweeps: the 15-min loop and HTTP /vol/vacuum handler
        # threads must never overlap-compact the same volume (the second
        # compact would race the first commit's makeup-diff replay)
        with self._vacuum_sweep_lock:
            return self._vacuum_once_locked(threshold)

    def _vacuum_once_locked(self, threshold: float) -> int:
        compacted = 0
        for dn in self.topology.data_nodes():
            for vid, info in list(dn.volumes.items()):
                if info.read_only:
                    continue
                locations = self.topology.lookup(info.collection, vid) or [dn]
                try:
                    # phase 1: every replica must be above threshold
                    ratios = []
                    for node in locations:
                        with rpc.dial(self._node_grpc(node)) as ch:
                            resp = rpc.volume_stub(ch).VacuumVolumeCheck(
                                volume_pb2.VacuumVolumeCheckRequest(volume_id=vid),
                                timeout=30,
                            )
                        ratios.append(resp.garbage_ratio)
                    if not ratios or min(ratios) < threshold:
                        continue
                    # no write fence needed: each replica's compact
                    # snapshots without blocking writes and its commit
                    # replays the catch-up diff under the volume lock
                    # (volume_vacuum.go:78-133 Compact2 + makeupDiff)
                    for node in locations:
                        with rpc.dial(self._node_grpc(node)) as ch:
                            rpc.volume_stub(ch).VacuumVolumeCompact(
                                volume_pb2.VacuumVolumeCompactRequest(
                                    volume_id=vid
                                ),
                                timeout=600,
                            )
                    for node in locations:
                        with rpc.dial(self._node_grpc(node)) as ch:
                            rpc.volume_stub(ch).VacuumVolumeCommit(
                                volume_pb2.VacuumVolumeCommitRequest(
                                    volume_id=vid
                                ),
                                timeout=600,
                            )
                    compacted += 1
                except grpc.RpcError:
                    # phase 4: abandon scratch files on the replicas
                    for node in locations:
                        try:
                            with rpc.dial(self._node_grpc(node)) as ch:
                                rpc.volume_stub(ch).VacuumVolumeCleanup(
                                    volume_pb2.VacuumVolumeCleanupRequest(
                                        volume_id=vid
                                    ),
                                    timeout=30,
                                )
                        except grpc.RpcError:
                            pass
        return compacted

    def _vacuum_loop(self) -> None:
        while not self._stop_event.wait(self.vacuum_interval):
            if self.is_leader:
                try:
                    self._vacuum_once()
                except Exception:  # noqa: BLE001 - loop must survive
                    pass

    def _liveness_loop(self) -> None:
        """Sweep out data nodes whose beats stopped arriving without a
        stream teardown (the stream-break path at Heartbeat's finally
        covers clean deaths; this covers frozen/half-open ones)."""
        interval = max(1.0, self.node_timeout / 3)
        while not self._stop_event.wait(interval):
            if not self.is_leader:
                continue
            now = time.time()
            for dn in self.topology.data_nodes():
                with self._node_lock:
                    if dn.parent is None:  # a teardown beat us to it
                        continue
                    if not (
                        dn.last_seen and now - dn.last_seen > self.node_timeout
                    ):
                        continue
                    wlog.warning(
                        "master: node %s silent for %.0fs; unregistering",
                        dn.url,
                        now - dn.last_seen,
                    )
                    vids = list(dn.volumes)
                    self.topology.unregister_data_node(dn)
                    self.health.note_dead(dn.url)
                    if vids:
                        self._broadcast(dn.url, dn.public_url, [], vids)

    def start(self) -> None:
        self._grpc_server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        self._grpc_server.add_generic_rpc_handlers(
            (rpc.servicer_handler(rpc.MASTER_SERVICE, rpc.MASTER_METHODS, self),)
        )
        if self._raft is not None:
            self._grpc_server.add_generic_rpc_handlers(
                (
                    rpc.servicer_handler(
                        rpc.RAFT_SERVICE, rpc.RAFT_METHODS, self._raft
                    ),
                )
            )
        rpc.add_port(self._grpc_server, f"{self.host}:{self.grpc_port}")
        self._grpc_server.start()
        if self._raft is not None:
            self._raft.start()

        self._http_server = WeedHTTPServer(
            (self.host, self.port), self._http_handler_class()
        )
        # tracing plane: assign/lookup hops get spans + request metrics
        self._http_server.trace_name = "master"
        self._http_server.trace_node = f"{self.host}:{self.port}"
        threading.Thread(target=self._http_server.serve_forever, daemon=True).start()
        if self.vacuum_interval > 0:
            threading.Thread(target=self._vacuum_loop, daemon=True).start()
        if self.node_timeout > 0:
            threading.Thread(target=self._liveness_loop, daemon=True).start()
        if self.repair is not None:
            self.repair.start()
        if self.telemetry is not None:
            self.telemetry.start()
        if self.tier is not None:
            self.tier.start()
        # continuous sampling profiler (telemetry/profiler.py): every
        # daemon serves /debug/profile; WEED_PROF=0 opts the process out
        from seaweedfs_tpu.telemetry import profiler

        profiler.ensure_started()

    def stop(self) -> None:
        self._stop_event.set()
        if self.tier is not None:
            self.tier.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.repair is not None:
            self.repair.stop()
        if self._raft is not None:
            self._raft.stop()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
