"""The shared blob write path: request → Needle, and replica fan-out.

Factored out of the volume server's POST handler so the `-shardWrites`
write workers (server/volume_workers.py) build byte-identical needles
with the exact semantics of the lead — multipart forms
(needle.go:85 ParseUpload), mime/name flags, JPEG orientation fixing,
transparent + pre-gzipped compression, chunk-manifest flag, Seaweed-*
pairs, ts=/ttl= params — and run the same replica fan-out
(store_replicate.go:44-80) when they own the first hop of a write.
"""

from __future__ import annotations

import json
import os
import time

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import (
    FLAG_HAS_LAST_MODIFIED_DATE,
    FLAG_HAS_PAIRS,
    FLAG_IS_CHUNK_MANIFEST,
    Needle,
)

try:  # the one-pass C POST hot loop (native/post.c); None = Python only
    from seaweedfs_tpu.native import needle_ext as _needle_ext
except ImportError:  # pragma: no cover - no compiler on host
    _needle_ext = None
if _needle_ext is not None and not hasattr(_needle_ext, "post"):
    _needle_ext = None  # stale artifact without the post entry

# kill switch for A/B measurement and the byte-identity tests
# (WEED_NATIVE_POST=0 forces every write through the Python path)
NATIVE_POST_ENABLED = os.environ.get("WEED_NATIVE_POST", "1") != "0"

# The write-path stage names, identical on the C hot loop and the
# Python fallback (docs/TRACING.md): a bench `--trace` breakdown and a
# `/debug/traces` span read the same whichever path served the write.
WRITE_STAGES = ("parse", "assemble", "crc", "pwrite", "reply")


def try_native_post(
    v,
    fid: FileId,
    q: dict,
    body: bytes,
    headers,
    url_filename: str = "",
    fix_jpg_orientation: bool = False,
    stages: dict | None = None,
) -> bytes | None:
    """The volume POST hot path as ONE native call: payload extraction
    (multipart or raw) → needle assembly → CRC32-C → pwrite at the
    append cursor → 201 reply bytes, all with the GIL released
    (native/post.c). Returns the reply body, or None when the request
    needs the pure-Python path (build_upload_needle + write_needle) —
    which produces byte-identical .dat/.idx/reply output for everything
    the C path does handle (tests/test_native_post.py).

    Caller contract: `v` is a storage.volume.Volume (or None). The
    needle map update + .idx append stay in Python (they are dict/16-
    byte-append cheap); everything O(body) is the C pass."""
    if (
        _needle_ext is None
        or not NATIVE_POST_ENABLED
        or v is None
        or getattr(v, "_fd", None) is None
        or v.read_only
        or v.version not in (2, 3)
        or v.ttl.count != 0  # volume-level TTL injection: Python path
        or q.get("ttl")  # per-needle TTL parse: Python path
    ):
        return None
    base_flags = FLAG_HAS_LAST_MODIFIED_DATE
    if q.get("cm") == "true":
        base_flags |= FLAG_IS_CHUNK_MANIFEST
    pairs = b""
    pair_map = {
        k[8:]: val
        for k, val in headers.items()
        if k.lower().startswith("seaweed-")
    }
    if pair_map:
        pairs = json.dumps(pair_map).encode()
        if len(pairs) >= 65536:
            pairs = b""  # dropped silently, as build_upload_needle does
        else:
            base_flags |= FLAG_HAS_PAIRS
    try:
        last_modified = int(q.get("ts", "") or 0) or int(time.time())
    except ValueError:
        last_modified = int(time.time())
    ctype = headers.get("content-type", "") or ""
    raw_gz = headers.get("content-encoding", "").lower() == "gzip"
    try:
        ctype_b = ctype.encode("latin-1")
        q_name_b = (q.get("filename", "") or "").encode("ascii")
        url_name_b = (url_filename or "").encode("ascii")
    except UnicodeEncodeError:
        return None  # non-latin1 header / non-ascii names: Python path
    with v._lock:
        if v.read_only:
            return None
        if v.nm.get(fid.key) is not None:
            return None  # overwrite/dedup/cookie semantics: Python path
        offset = v._append_end
        if offset % t.NEEDLE_PADDING_SIZE:
            return None  # realign via the Python append path
        append_at_ns = v._now_ns()
        res = _needle_ext.post(
            body,
            ctype_b,
            1 if raw_gz else 0,
            q_name_b,
            url_name_b,
            pairs,
            base_flags,
            fid.cookie,
            fid.key,
            v.version,
            last_modified,
            append_at_ns,
            v._fd,
            offset,
            1 if fix_jpg_orientation else 0,
        )
        if res is None:
            return None
        reply, total, size, stage_secs = res
        if stages is not None:
            stages.update(zip(WRITE_STAGES, stage_secs))
        v._append_end = offset + total
        v.last_append_at_ns = append_at_ns
        v.nm.put(fid.key, t.offset_to_units(offset), size)
        return reply


def build_upload_needle(
    fid: FileId,
    q: dict,
    body: bytes,
    headers,
    url_filename: str = "",
    fix_jpg_orientation: bool = False,
    stages: dict | None = None,
) -> tuple[Needle | None, str, str | None]:
    """(needle, filename, error): error is a client-facing 400 message.

    `headers` is any case-insensitive mapping with .get and .items
    (FastHeaders on the data plane). A `stages` dict collects the
    tracing plane's "parse" (payload extraction) and "assemble" (needle
    field construction) wall seconds — the Python-path counterparts of
    the C hot loop's identically-named stages; "crc"/"pwrite" land in
    Volume.write_needle, "reply" at the handler's formatting site."""
    t0 = time.perf_counter() if stages is not None else 0.0
    ctype = headers.get("content-type", "")
    part_filename = ""
    is_gzipped = False
    if ctype[:19].lower() == "multipart/form-data":
        from seaweedfs_tpu.util.multipart import MalformedUpload, parse_upload

        try:
            part = parse_upload(body, ctype)
        except MalformedUpload as e:
            return None, "", str(e)
        data, ctype, part_filename = part.data, part.mime, part.filename
        is_gzipped = part.is_gzipped
    else:
        data = body
        # raw bodies may arrive pre-gzipped (Content-Encoding)
        is_gzipped = headers.get("content-encoding", "").lower() == "gzip"
    if stages is not None:
        t1 = time.perf_counter()
        stages["parse"] = t1 - t0
        t0 = t1
    n = Needle(cookie=fid.cookie, id=fid.key, data=data)
    if ctype and len(ctype) < 256 and ctype != "application/octet-stream":
        n.mime = ctype.encode()
        n.set_has_mime()
    fname = q.get("filename", "") or part_filename or url_filename
    if fname and len(fname) < 256:
        n.name = fname.encode()
        n.set_has_name()
        if fix_jpg_orientation and fname.lower().endswith((".jpg", ".jpeg")):
            from seaweedfs_tpu import images

            n.data = images.fix_jpg_orientation(bytes(n.data))
    if is_gzipped:
        n.set_gzipped()
    elif len(n.data) > 128:
        # transparent server-side compression when the type says it
        # pays (needle_parse_multipart.go:86-97 + util/compression.go
        # IsGzippable); deterministic, so replica fan-out re-derives
        # identical needles
        from seaweedfs_tpu.util.compression import is_gzippable

        fext = os.path.splitext(fname)[1] if fname else ""
        if is_gzippable(fext, ctype or "", bytes(n.data)):
            import gzip as _gzip

            # mtime=0: replicas re-derive the needle from the raw
            # body, so the stream must be identical
            # weedlint: ignore[hot-loop-gil-span] — transparent compression is the write contract (byte-identical replicas); the C tier declines these bodies by design
            packed = _gzip.compress(bytes(n.data), 6, mtime=0)
            if len(packed) < len(n.data):
                n.data = packed
                n.set_gzipped()
    if q.get("cm") == "true":
        n.set_is_chunk_manifest()
    # Seaweed-* request headers persist as needle pairs
    # (needle.go:37-42 PairNamePrefix + :101-113)
    pair_map = {
        k[8:]: v for k, v in headers.items() if k.lower().startswith("seaweed-")
    }
    if pair_map:
        pairs = json.dumps(pair_map).encode()
        if len(pairs) < 65536:
            n.pairs = pairs
            n.set_has_pairs()
    # ts= overrides the modification stamp; ttl= stores a per-needle
    # ttl (needle.go:79-81)
    try:
        n.last_modified = int(q.get("ts", "") or 0) or int(time.time())
    except ValueError:
        n.last_modified = int(time.time())
    n.set_has_last_modified_date()
    ttl_param = q.get("ttl", "")
    if ttl_param:
        from seaweedfs_tpu.storage.ttl import TTL

        try:
            n.ttl = TTL.parse(ttl_param)
            if n.ttl.count:
                n.set_has_ttl()
        except ValueError:
            pass
    if stages is not None:
        stages["assemble"] = time.perf_counter() - t0
    return n, fname, None


def replicate_to_peers(
    fid: FileId,
    q: dict,
    method: str,
    body: bytes,
    headers,
    locations: list[str],
    on_fail=None,
) -> str | None:
    """Fan the original write to the replica `locations` (already
    excluding the sender) with type=replicate so peers store without
    re-fanning (store_replicate.go:44-80). Returns an error message or
    None; all-or-error like the reference (a failed replica fails the
    write).

    `on_fail(url, path_with_query, error, status)` is the weedguard
    hinted-handoff seam (docs/HEALTH.md): called for a peer whose hop
    failed at the TRANSPORT level or with a 5xx (`status` is None for
    transport failures) — returning True absorbs that peer's failure
    (the caller durably spooled the request for replay on heal) so one
    sick replica no longer fails the whole write. Semantic rejections
    (4xx: bad auth, cookie mismatch) never reach it — a reachable peer
    refusing the write is a real error, not an outage."""
    import urllib.error
    import urllib.request
    from urllib.parse import urlencode

    from seaweedfs_tpu import trace

    params = {k: v for k, v in q.items() if k != "type"}
    params["type"] = "replicate"
    # replica fan-out is an internal hop: the peer's span must parent
    # under THIS server's span, not the client's original header
    trace_hdr = trace.header_value()
    path_q = f"/{fid}?{urlencode(params)}"
    for url in locations:
        try:
            req = urllib.request.Request(
                f"http://{url}{path_q}",
                data=body if method == "POST" else None,
                method=method,
            )
            if trace_hdr:
                req.add_header(trace.TRACE_HEADER, trace_hdr)
            # FastHeaders stores keys lowercased; look up both
            # spellings so a plain-dict caller keeps working too
            ct = headers.get("Content-Type") or headers.get("content-type")
            if ct:
                req.add_header("Content-Type", ct)
            ce = headers.get("Content-Encoding") or headers.get(
                "content-encoding"
            )
            if ce:  # pre-gzipped uploads must stay flagged on replicas
                req.add_header("Content-Encoding", ce)
            for hk, hv in headers.items():
                if hk.lower().startswith("seaweed-"):
                    req.add_header(hk, hv)  # pairs replicate too
            auth = headers.get("Authorization") or headers.get("authorization")
            if auth:  # keep the write jwt valid on the replica hop
                req.add_header("Authorization", auth)
            # weedlint: ignore[no-deadline] — one bounded 10 s replica hop inside the already-deadlined POST dispatch; Request carries per-needle headers http_call lacks
            with urllib.request.urlopen(req, timeout=10) as r:
                if r.status >= 300:
                    return f"replica {url} returned {r.status}"
        except urllib.error.HTTPError as e:
            if e.code >= 500 and on_fail is not None and on_fail(
                url, path_q, f"HTTP {e.code}", e.code
            ):
                continue
            return f"replica {url} returned {e.code}"
        except OSError as e:
            if on_fail is not None and on_fail(url, path_q, str(e), None):
                continue
            return f"replica {url} failed: {e}"
    return None


def check_write_auth(guard, path: str, headers, client_ip: str) -> str | None:
    """JWT/white-list gate on mutating requests; None = allowed, else
    the 401 message (security/guard.go WhiteList+Secure wrapping of the
    write handlers). The jwt claim must match the request fid; every
    addressing form normalizes to the comma form the assign minted the
    token for (a _delta suffix stays part of the claimed id). Shared by
    the lead handler and the -shardWrites workers so sharded local
    writes enforce the same signature check."""
    if guard is None or not guard.is_write_active:
        return None
    from urllib.parse import parse_qs

    from seaweedfs_tpu.security import UnauthorizedError, jwt_from_headers
    from seaweedfs_tpu.storage.file_id import parse_url_path

    bare, _, qs = path.partition("?")
    token = jwt_from_headers(parse_qs(qs), headers)
    candidates = [bare.lstrip("/")]
    vid, fid_str, _fn, _ext, vid_only = parse_url_path(bare)
    if fid_str and not vid_only:
        comma = f"{vid},{fid_str}"
        if comma not in candidates:
            candidates.append(comma)
    err = None
    for cand in candidates:
        try:
            guard.check_write(client_ip, token, cand)
            return None
        except UnauthorizedError as e:
            err = e
    return str(err)
