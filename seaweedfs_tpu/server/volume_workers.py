"""SO_REUSEPORT read workers: per-core scaling for the volume data plane.

The reference volume server scales across cores for free — Go
schedules request goroutines onto every CPU (bazil-style concurrency
behind weed/server/volume_server_handlers_read.go). A CPython process
is pinned to one core by the GIL, so `volume -workers N` spawns N-1
extra *read worker* processes that share the SAME host:port through
SO_REUSEPORT (the kernel distributes accepted connections across the
listeners — the mechanism nginx/envoy use for per-core workers):

  * worker processes serve plain GET/HEAD straight from the shared
    volume directories — each opens the volumes read-only and keeps
    its needle map fresh by replaying the append-only `.idx` tail
    (one fstat per lookup; an inode change means the lead vacuumed
    the volume, which triggers a clean reopen);
  * everything else — writes, deletes, EC/chunk-manifest reads, the
    UI/status pages, image resizing — is proxied over a pooled
    keep-alive connection to the lead's internal listener, so the
    whole surface stays available on every accepted connection;
  * the LEAD (worker 0) remains the one full volume server: it runs
    the gRPC admin plane and sends the heartbeats. Its inventory
    covers the shared directories, so the master sees one data node.
  * with `-shardWrites`, workers additionally OWN the writes for vids
    with vid % N == their index: they append those volumes'
    .dat/.idx themselves (single-writer-per-volume, partitioned
    across processes), fan out replication on first-hop writes, and
    hand ownership back to the lead before any file-rewriting admin
    op (the /__shard/release handshake; see OPERATIONS.md round 5).

Read-your-writes holds because every writer appends the `.idx` entry
(and flushes it) before replying 201, and readers re-check the idx
size on every lookup miss-or-hit cycle. Vacuum is safe because a
reader keeps serving the old inode until the commit renames land,
then reopens (with retry — the reopen itself can straddle a commit).
"""

from __future__ import annotations

import os
import socket
import threading

from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage.disk_location import parse_volume_file_name
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import Needle  # noqa: F401 (re-export for tests)
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NeedleNotFound,
    Volume,
)
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.httpd import (
    JSON_HDR,
    FastHandler,
    WeedHTTPServer,
    etag_matches,
    fast_query,
)

_HOP_HEADERS = {
    "connection",
    "keep-alive",
    "transfer-encoding",
    "content-length",
    "host",
    # internal ownership signaling: a client-supplied copy must never
    # ride through the proxy (the lead would seize the vid); _proxy
    # re-adds its own AFTER this filter when the owner declined
    "x-shard-hop",
}


class VolumeReleased(RuntimeError):
    """Raised under the volume lock when a write's vid was handed back
    to the lead after the caller's ownership gate (release/write race:
    the release ack drains this lock, so any append the lead's refresh
    could miss must abort and re-route instead)."""


class SharedReadVolume:
    """A read-only view of a volume whose writer lives in the lead
    process, kept fresh from the on-disk `.idx` (see module docstring)."""

    def __init__(self, directory: str, vid: int, collection: str = ""):
        self.directory = directory
        self.vid = vid
        self.collection = collection
        self._lock = threading.Lock()
        self._open()

    _ENTRY = 16  # NEEDLE_MAP_ENTRY_SIZE
    _OPEN_RETRIES = 40
    _OPEN_RETRY_S = 0.005

    def _open(self) -> None:
        import time as _time

        from seaweedfs_tpu.storage.needle import CorruptNeedle
        from seaweedfs_tpu.storage.volume import volume_base_name

        # stat BEFORE loading: entries appended between the stat and
        # the load replay twice, which is safe (idx replay is last-wins
        # idempotent; metrics are lead-owned). Statting after would
        # skip the [loaded, stat] window forever.
        #
        # The open itself RETRIES: a reopen can straddle a vacuum
        # commit (commit_compact replaces .dat then .idx), catching an
        # inconsistent name pair — e.g. the previous index alongside
        # the next, smaller compacted .dat, which Volume's integrity
        # check rejects (found by TestTornReadUnderVacuum: ~1 in 50
        # tight commits). Each retry re-stats, so the loop converges on
        # the post-commit pair; pinned fds keep already-open volumes
        # safe — only this reopen window needs the loop.
        self._idx_path = (
            volume_base_name(self.directory, self.collection, self.vid) + ".idx"
        )
        for attempt in range(self._OPEN_RETRIES):
            st = os.stat(self._idx_path)
            try:
                vol = Volume(
                    self.directory, self.vid, self.collection, create=False
                )
            except (CorruptNeedle, OSError, ValueError):
                if attempt == self._OPEN_RETRIES - 1:
                    raise
                # weedlint: ignore[hot-loop-sleep] — bounded 40×5 ms vacuum-commit reopen retry; the alternative is failing the read
                _time.sleep(self._OPEN_RETRY_S)
                continue
            # the pair must still be the one we statted: an idx swapped
            # in mid-open would replay with wrong offsets
            st2 = os.stat(self._idx_path)
            if st2.st_ino != st.st_ino:
                vol.close()
                # weedlint: ignore[hot-loop-sleep] — same bounded reopen retry: the idx swapped mid-open, converges within one commit
                _time.sleep(self._OPEN_RETRY_S)
                continue
            self._idx_ino = st.st_ino
            self._replayed = st.st_size - (st.st_size % self._ENTRY)
            self._vol = vol
            return
        raise OSError(f"volume {self.vid}: no consistent .dat/.idx pair")

    def _refresh(self) -> None:
        st = os.stat(self._idx_path)
        if st.st_ino != self._idx_ino:
            # vacuum/compact committed: whole new .dat/.idx pair
            old = self._vol
            self._open()
            old.close()
            return
        if st.st_size > self._replayed:
            with open(self._idx_path, "rb") as f:
                f.seek(self._replayed)
                tail = f.read(st.st_size - self._replayed)
            # whole entries only: a read racing the lead's 16-byte
            # append may end mid-entry, and advancing past those bytes
            # would shift every later decode
            usable = len(tail) - (len(tail) % self._ENTRY)
            for key, offset, size in idx_codec.iter_entries(tail[:usable]):
                self._vol.nm._replay(key, offset, size)
            self._replayed += usable

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        from seaweedfs_tpu.storage.needle import CorruptNeedle, CookieMismatch

        with self._lock:
            self._refresh()
        try:
            return self._vol.read_needle(needle_id, cookie=cookie)
        except (CorruptNeedle, CookieMismatch):
            # reopen-and-retry: a reopen that straddled a commit's
            # dat→idx rename window can pair an index with a dat whose
            # offsets moved — the per-needle cookie+CRC catches it
            # here; a fresh consistent pair must agree. A GENUINE bad
            # cookie / corrupt blob fails identically on the retry and
            # the original error propagates.
            with self._lock:
                old = self._vol
                self._open()
                if old is not self._vol:
                    old.close()
            return self._vol.read_needle(needle_id, cookie=cookie)

    # --- -shardWrites owner side -----------------------------------------
    # When this worker OWNS the vid (vid % n_writers == index), the
    # wrapped Volume is the volume's single writer: appends go through
    # the same Volume.write_needle/delete_needle as the lead's (dat
    # pwrite + idx append + flush before the 201 — read-your-writes for
    # every other process's tail replay). _refresh first, so overwrite
    # cookie checks and dedup see anything the lead wrote before
    # ownership started.
    def write_needle(
        self, n: Needle, precheck=None, stages=None
    ) -> tuple[int, bool]:
        with self._lock:
            if precheck is not None and not precheck():
                # ownership was released between the caller's gate and
                # this lock: the write must go to the new owner, not
                # land here after the lead's catch-up refresh
                raise VolumeReleased(self.vid)
            self._refresh()
            _, size, unchanged = self._vol.write_needle(n, stages=stages)
            # own append is already in the map: advance the replay
            # cursor past it or the next _refresh re-replays it and
            # double-counts the map metrics
            self._replayed = self._vol.nm.index_file_size()
            return size, unchanged

    def write_needles(
        self, entries, precheck=None, durable: bool = False
    ) -> list:
        """Batch counterpart of write_needle for the worker-side group
        commit window (qos/group_commit.py): ONE ownership precheck and
        refresh cover the whole batch — the release ack drains this
        lock, so the batch either lands wholly before the handback or
        aborts wholesale and re-routes to the new owner."""
        with self._lock:
            if precheck is not None and not precheck():
                raise VolumeReleased(self.vid)
            self._refresh()
            results = self._vol.write_needles(entries, durable=durable)
            self._replayed = self._vol.nm.index_file_size()
            return results

    def delete_needle(self, n: Needle, precheck=None) -> int:
        with self._lock:
            if precheck is not None and not precheck():
                raise VolumeReleased(self.vid)
            self._refresh()
            size = self._vol.delete_needle(n)
            self._replayed = self._vol.nm.index_file_size()
            return size

    def native_post(
        self, fid, q, body, headers, url_filename, precheck=None,
        stages=None,
    ) -> bytes | None:
        """The C one-pass POST (write_path.try_native_post) under this
        wrapper's refresh + release-precheck discipline. None = take
        the Python slow path (same bytes either way)."""
        from seaweedfs_tpu.server import write_path

        with self._lock:
            if precheck is not None and not precheck():
                raise VolumeReleased(self.vid)
            self._refresh()
            reply = write_path.try_native_post(
                self._vol, fid, q, body, headers, url_filename,
                fix_jpg_orientation=True, stages=stages,
            )
            if reply is not None:
                # own append is already in the map: advance the replay
                # cursor past it (same bookkeeping as write_needle)
                self._replayed = self._vol.nm.index_file_size()
            return reply

    @property
    def volume(self):
        return self._vol

    def close(self) -> None:
        self._vol.close()


class _CommitVolume:
    """The lead-Volume surface qos.group_commit.GroupCommitter expects
    (`.id`, write_needle → (offset, size, unchanged), write_needles,
    commit) over a SharedReadVolume plus its ownership precheck.
    Commit windows key on `.id`, so concurrent owned writes against
    one vid coalesce no matter which request built the facade."""

    __slots__ = ("_srv", "_precheck")

    def __init__(self, srv: SharedReadVolume, precheck):
        self._srv = srv
        self._precheck = precheck

    @property
    def id(self):  # noqa: A003 — mirrors storage.Volume.id
        return self._srv.vid

    def write_needle(self, n, stages=None):
        size, unchanged = self._srv.write_needle(
            n, precheck=self._precheck, stages=stages
        )
        return 0, size, unchanged

    def write_needles(self, entries, durable: bool = False):
        return self._srv.write_needles(
            entries, precheck=self._precheck, durable=durable
        )

    def commit(self):
        self._srv.volume.commit()


class VolumeReadWorker:
    """One worker process: shared-port listener + blob read fast path."""

    def __init__(
        self,
        directories: list[str],
        host: str,
        port: int,
        lead: str,
        worker_port: int = 0,
        shard_writes: bool = False,
        writer_index: int = 0,
        n_writers: int = 1,
        master: str = "",
        internal_port: int = 0,
        guard=None,
        admission_rate: float = 0.0,
        admission_burst: float = 0.0,
        admission_inflight: int = 0,
        admission_procs: int = 1,
        admission_shm_path: str = "",
        commit_window_us: int = 0,
        commit_bytes: int = 4 << 20,
        commit_batch: int = 64,
        commit_fsync: bool = False,
    ):
        self.directories = directories
        self.host = host
        self.port = port
        self.lead = lead  # host:port of the lead's internal listener
        # QoS admission control (docs/QOS.md): with -admissionShmPath
        # every SO_REUSEPORT sibling (lead included) charges ONE
        # mmap'd bucket per client key, so the GLOBAL budget holds no
        # matter how the kernel spreads connections — and the C epoll
        # loop sheds natively. Without it, each member enforces rate/N
        # (exact only under uniform connection spread). Before either,
        # only the lead gated and N-1 of every N connections bypassed
        # admission entirely (ROADMAP tail-latency follow-on).
        self.admission = None
        if admission_rate > 0 or admission_inflight > 0:
            from seaweedfs_tpu.qos.admission import AdmissionController

            self.admission = AdmissionController(
                rate=admission_rate,
                burst=admission_burst,
                max_inflight=admission_inflight,
                procs=admission_procs,
                label=f"volume-worker-{writer_index}",
                shm_path=admission_shm_path,
            )
        # QoS group commit on the worker-owned write path (-shardWrites
        # + -commitWindowUs/-commitFsync): concurrent POSTs for vids
        # this worker owns coalesce into one pwritev + at most one
        # fsync, same as the lead's (qos/group_commit.py). The C POST
        # fast path declines while a committer is installed, exactly
        # like the lead's do_POST.
        self.group_commit = None
        if shard_writes and (commit_window_us > 0 or commit_fsync):
            from seaweedfs_tpu.qos.group_commit import GroupCommitter

            self.group_commit = GroupCommitter(
                window_us=commit_window_us,
                max_bytes=commit_bytes,
                max_batch=commit_batch,
                fsync=commit_fsync,
            )
        self.worker_port = worker_port  # optional private listener (tests)
        # -shardWrites: this worker OWNS writes for vids with
        # vid % n_writers == writer_index (lead is writer 0) — see
        # VolumeServer's shard_writes comment for the ownership story.
        # `released` holds vids handed back to the lead (admin ops,
        # takeovers); their writes proxy like everything else.
        self.shard_writes = shard_writes
        self.writer_index = writer_index
        self.n_writers = max(1, n_writers)
        self.master = master  # for replica fan-out lookups on owned writes
        self.internal_port = internal_port  # own release/control listener
        self.guard = guard  # same security.toml Guard as the lead
        self.released: set[int] = set()
        self._release_lock = threading.Lock()
        self._volumes: dict[int, SharedReadVolume] = {}
        self._vol_lock = threading.Lock()
        self._internal_server: WeedHTTPServer | None = None
        self._servers: list[WeedHTTPServer] = []
        self._threads: list[threading.Thread] = []

    # --- volume discovery ------------------------------------------------
    def _find_volume(self, vid: int) -> SharedReadVolume | None:
        v = self._volumes.get(vid)
        if v is not None:
            return v
        with self._vol_lock:
            v = self._volumes.get(vid)
            if v is not None:
                return v
            for d in self.directories:
                try:
                    names = os.listdir(d)
                except OSError:
                    continue
                for name in names:
                    parsed = parse_volume_file_name(name)
                    if parsed is None or parsed[1] != vid:
                        continue
                    try:
                        v = SharedReadVolume(d, vid, parsed[0])
                    except (OSError, ValueError, RuntimeError):
                        # unreadable, mid-commit, or remote-tiered
                        # (workers carry no backend config) — the lead
                        # serves it via the proxy path
                        return None
                    self._volumes[vid] = v
                    return v
        return None

    def _drop_volume(self, vid: int) -> None:
        with self._vol_lock:
            v = self._volumes.pop(vid, None)
        if v is not None:
            try:
                v.close()
            except OSError:
                pass

    # --- HTTP ------------------------------------------------------------
    def _make_handler(self):
        worker = self

        class Handler(FastHandler):

            def do_GET(self):
                path, _, qs = self.path.partition("?")
                fid_part = path.lstrip("/")
                if "," in fid_part and "/" not in fid_part:
                    q = fast_query(qs)
                    if not ("width" in q or "height" in q):
                        try:
                            fid = FileId.parse(fid_part)
                        except ValueError:
                            fid = None
                        if fid is not None and self._serve_blob(fid):
                            return
                self._proxy()

            do_HEAD = do_GET

            def do_POST(self):
                if self.path.startswith("/__shard/release"):
                    return self._shard_release()
                # body read ONCE: the owned-write path consumes the
                # socket; a declining fallback must hand the SAME bytes
                # to the proxy, not re-read a drained connection
                length = int(self.headers.get("content-length", "0") or 0)
                body = self.rfile.read(length)
                self._hop_owner_declined = False
                if worker.shard_writes and self._try_owned_write("POST", body):
                    return
                self._proxy(body=body)

            def do_DELETE(self):
                self._hop_owner_declined = False
                if worker.shard_writes and self._try_owned_write("DELETE", b""):
                    return
                self._proxy(body=b"")

            def _shard_release(self):
                """Lead handshake: stop writing this vid forever; the
                lead takes ownership once we acknowledge. Internal
                listener ONLY — on the public port an anonymous client
                could strip write ownership vid by vid."""
                if (
                    worker._internal_server is None
                    or self.server is not worker._internal_server
                ):
                    return self._json({"error": "not found"}, 404)
                q = fast_query(self.path.partition("?")[2])
                try:
                    vid = int(q.get("vid", ""))
                except ValueError:
                    return self._json({"error": "bad vid"}, 400)
                with worker._release_lock:
                    worker.released.add(vid)
                    v = worker._volumes.get(vid)
                # in-flight owned writes hold the volume lock (their
                # under-lock precheck ran before our released.add, so
                # they are appending); taking it once AFTER dropping the
                # release lock (writers acquire release_lock inside
                # v._lock — same order here would deadlock) means the
                # ack orders after every append the lead must replay
                if v is not None:
                    with v._lock:
                        pass
                self._json({"released": vid})

            def _try_owned_write(self, method: str, body: bytes) -> bool:
                """True when this worker owned the vid and handled the
                write/delete locally (byte-identical semantics to the
                lead via server.write_path)."""
                from seaweedfs_tpu.server import write_path
                from seaweedfs_tpu.storage.file_id import (
                    parse_path_fid,
                    parse_url_path,
                )

                path, _, qs = self.path.partition("?")
                try:
                    vid_s, fid_str, url_filename, _ext, vid_only = (
                        parse_url_path(path)
                    )
                    if vid_only or not fid_str:
                        return False
                    fid = parse_path_fid(vid_s, fid_str)
                except ValueError:
                    return False
                q = fast_query(qs)
                vid = fid.volume_id
                if vid % worker.n_writers != worker.writer_index:
                    return False
                self._hop_owner_declined = True  # owner from here on
                auth_err = write_path.check_write_auth(
                    worker.guard, self.path, self.headers,
                    self.client_address[0],
                )
                if auth_err is not None:
                    self._json({"error": auth_err}, 401)
                    return True
                with worker._release_lock:
                    if vid in worker.released:
                        return False
                v = worker._find_volume(vid)
                if v is None:
                    return False  # not on disk yet / mid-commit: lead's

                def still_owned():
                    # ONE ownership predicate for the delete, native,
                    # and Python write paths — they must never diverge
                    with worker._release_lock:
                        return vid not in worker.released

                if method == "DELETE":
                    return self._owned_delete(v, fid, q, still_owned)
                # C hot loop first; Python fallback below — both
                # branches converge on the ONE replicate-then-reply
                # tail (same shape as the lead's do_POST)
                req_span = getattr(self, "_trace_span", None)
                stages = {} if req_span is not None else None
                if worker.group_commit is not None:
                    # QoS group commit (docs/QOS.md): the C one-call
                    # append can't join a commit window (and fsync-only
                    # mode needs the post-write flush), so the fast
                    # path declines wholesale while a committer is
                    # installed — same policy as the lead's do_POST
                    reply = None
                else:
                    try:
                        reply = v.native_post(
                            fid, q, body, self.headers, url_filename,
                            precheck=still_owned, stages=stages,
                        )
                    except VolumeReleased:
                        return False  # re-route to the lead (new owner)
                    except (CookieMismatch, ValueError) as e:
                        # same contract as the Python branch below: a
                        # refresh/reopen failure (CorruptNeedle is a
                        # ValueError) answers 409, never a dropped socket
                        self._json({"error": str(e)}, 409)
                        return True
                    except OSError:
                        worker._drop_volume(vid)
                        return False
                if reply is None:
                    n, fname, err = write_path.build_upload_needle(
                        fid, q, body, self.headers, url_filename,
                        fix_jpg_orientation=True, stages=stages,
                    )
                    if err is not None:
                        self._json({"error": err}, 400)
                        return True
                    try:
                        if worker.group_commit is not None:
                            _, size, unchanged = worker.group_commit.write(
                                _CommitVolume(v, still_owned), n,
                                stages=stages,
                            )
                        else:
                            size, unchanged = v.write_needle(
                                n, precheck=still_owned, stages=stages
                            )
                    except VolumeReleased:
                        return False  # re-route to the lead (new owner)
                    except (CookieMismatch, ValueError) as e:
                        self._json({"error": str(e)}, 409)
                        return True
                    except OSError:
                        worker._drop_volume(vid)
                        return False
                    import json as _json

                    reply = (
                        b'{"name": %s, "size": %d, "eTag": "%s"}'
                        % (_json.dumps(fname).encode(), size, n.etag().encode())
                    )
                if stages:
                    req_span.add_stages(stages)
                if q.get("type") != "replicate":
                    err = self._replicate_owned(v, fid, q, body)
                    if err:
                        self._json({"error": err}, 500)
                        return True
                self.fast_reply(201, reply, JSON_HDR)
                return True

            def _owned_delete(self, v, fid, q, still_owned) -> bool:
                n = Needle(cookie=fid.cookie, id=fid.key)
                try:
                    existing = v.read_needle(fid.key, cookie=fid.cookie)
                    if existing.is_chunked_manifest():
                        # manifest cascade needs the lead's fan-out
                        return False
                    v.delete_needle(n, precheck=still_owned)
                except VolumeReleased:
                    return False
                except NeedleNotFound:
                    self._json({"size": 0}, 404)
                    return True
                except CookieMismatch as e:
                    self._json({"error": str(e)}, 409)
                    return True
                except OSError:
                    worker._drop_volume(fid.volume_id)
                    return False
                # first-hop deletes fan out to replica peers exactly
                # like the lead's do_DELETE — an acknowledged delete
                # that skipped its replicas would resurrect there
                # (reference ReplicatedDelete, store_replicate.go)
                if q.get("type") != "replicate":
                    err = self._replicate_owned(
                        v, fid, q, b"", method="DELETE"
                    )
                    if err:
                        self._json({"error": err}, 500)
                        return True
                # 202 Accepted, matching the lead's do_DELETE reply
                self._json({"size": existing.size}, 202)
                return True

            def _replicate_owned(
                self, v, fid, q, body, method: str = "POST"
            ) -> str | None:
                """Replica fan-out for a write/delete this worker
                first-hop owns (store_replicate.go:44): peers looked up
                at the master, self excluded by the SHARED public
                host:port."""
                from seaweedfs_tpu.server import write_path

                rp = v.volume.super_block.replica_placement
                if rp.copy_count <= 1 or not worker.master:
                    return None
                from seaweedfs_tpu.client import operation as op

                try:
                    res = op.lookup(worker.master, str(fid.volume_id))
                except (OSError, RuntimeError) as e:
                    return f"replication lookup failed: {e}"
                if res.error:
                    return "replication lookup failed"
                me = f"{worker.host}:{worker.port}"
                locations = [
                    l["url"] for l in res.locations if l["url"] != me
                ]
                return write_path.replicate_to_peers(
                    fid, q, method, body, self.headers, locations
                )

            def _serve_blob(self, fid) -> bool:
                """True when served locally; False = hand to the proxy
                (unknown volume, EC volume, chunk manifest, expired…)."""
                v = worker._find_volume(fid.volume_id)
                if v is None:
                    return False
                try:
                    n = v.read_needle(fid.key, cookie=fid.cookie)
                except FileNotFoundError:
                    worker._drop_volume(fid.volume_id)
                    return False
                except CookieMismatch:
                    self._json({"error": "cookie mismatch"}, 404)
                    return True
                except NeedleNotFound:
                    self._json({"error": "not found"}, 404)
                    return True
                except (OSError, ValueError, RuntimeError):
                    worker._drop_volume(fid.volume_id)
                    return False
                if n.is_chunked_manifest():
                    return False  # manifest fan-in needs the lead's store
                if (
                    n.is_gzipped()
                    or n.has_pairs()
                    or self.headers.get("etag-md5") == "True"
                ):
                    # content-encoding negotiation, pair headers, and the
                    # md5-validator variant live in the lead's full
                    # read handler
                    return False
                if n.has_last_modified_date():
                    ims = self.headers.get("if-modified-since")
                    if ims:
                        from email.utils import parsedate_to_datetime

                        try:
                            t = parsedate_to_datetime(ims).timestamp()
                        except (TypeError, ValueError):
                            t = None
                        if t is not None and t >= n.last_modified:
                            self.fast_reply(304)
                            return True
                etag = f'"{n.etag()}"'
                # RFC 9110 §13.1.2: weak compare over a quote-aware
                # comma list (W/"…", multiple members, `*`) — the same
                # scanner the lead's do_GET and the C loop run
                if etag_matches(self.headers.get("if-none-match", ""), etag):
                    self.fast_reply(304)
                    return True
                # header assembly mirrors the lead's do_GET for a bare
                # fid URL (and the shared plan core the C arm serves
                # from) — octet-stream mimes stay implicit, extension
                # fallback, escaped filename — so a worker's threaded
                # reply is byte-identical to the lead's and to the C
                # fast path for the same needle
                headers = {
                    "ETag": etag,
                    "Content-Type": "application/octet-stream",
                }
                fname = (
                    n.name.decode("latin-1")
                    if n.has_name() and n.name
                    else ""
                )
                if (
                    n.has_mime()
                    and n.mime
                    and not n.mime.startswith(b"application/octet-stream")
                ):
                    headers["Content-Type"] = n.mime.decode("latin-1")
                elif fname:
                    import mimetypes
                    from os.path import splitext

                    ext = splitext(fname)[1]
                    guessed = (
                        mimetypes.types_map.get(ext.lower()) if ext else None
                    )
                    if guessed:
                        headers["Content-Type"] = guessed
                if fname:
                    escaped = fname.replace("\\", "\\\\").replace('"', '\\"')
                    headers["Content-Disposition"] = (
                        f'inline; filename="{escaped}"'
                    )
                if n.has_last_modified_date():
                    from seaweedfs_tpu.server.volume_server import _http_date

                    headers["Last-Modified"] = _http_date(n.last_modified)
                headers["Accept-Ranges"] = "bytes"
                data = n.data
                from seaweedfs_tpu.util.http_range import (
                    RangeNotSatisfiable,
                    parse_range,
                )

                try:
                    span = parse_range(self.headers.get("range", ""), len(data))
                except RangeNotSatisfiable:
                    self.fast_reply(
                        416, b"", {"Content-Range": f"bytes */{len(data)}"}
                    )
                    return True
                if span is None:
                    self.fast_reply(200, data, headers)
                else:
                    start, end = span
                    headers["Content-Range"] = (
                        f"bytes {start}-{end}/{len(data)}"
                    )
                    self.fast_reply(206, data[start : end + 1], headers)
                return True

            def _json(self, obj, status=200):
                import json

                self.fast_reply(status, json.dumps(obj).encode(), JSON_HDR)

            def _proxy(self, body: bytes | None = None):
                """Forward this request verbatim to the lead and relay
                the response (one pooled keep-alive conn per handler
                thread, via the client transport). `body` carries
                already-consumed request bytes (the owned-write path
                reads the socket before deciding to decline)."""
                from seaweedfs_tpu.client.operation import _drop_conn, _pooled_conn

                if body is None and self.command in ("POST", "PUT", "DELETE"):
                    try:
                        n = int(self.headers.get("content-length", "0"))
                    except ValueError:
                        n = 0
                    body = self.rfile.read(n) if n else b""
                fwd = {
                    k: v
                    for k, v in self.headers.items()
                    if k not in _HOP_HEADERS
                }
                # re-stamp the trace header with THIS hop's span so the
                # lead's span parents under the worker hop, keeping the
                # x-shard-hop forwarding chain on one trace
                from seaweedfs_tpu import trace as _trace

                _trace.inject(fwd)
                if getattr(self, "_hop_owner_declined", False):
                    # tells the lead: this request already visited the
                    # vid's OWNER, which declined (released volume,
                    # manifest cascade, mid-commit) — handle it there
                    # after taking ownership; never route it back. A
                    # NON-owner's proxy must NOT set this, or the lead
                    # would seize vids of healthy third workers
                    # (-workers >= 3).
                    fwd["x-shard-hop"] = "1"
                try:
                    c, reused = _pooled_conn(worker.lead, 30.0)
                    try:
                        c.send_request(self.command, self.path, body, fwd)
                        status, rheaders, data, will_close = c.read_response(
                            self.command
                        )
                    except OSError:
                        _drop_conn(worker.lead)
                        if not reused:
                            raise
                        c, _ = _pooled_conn(worker.lead, 30.0)
                        c.send_request(self.command, self.path, body, fwd)
                        status, rheaders, data, will_close = c.read_response(
                            self.command
                        )
                    if will_close:
                        _drop_conn(worker.lead)
                except OSError as e:
                    return self._json({"error": f"lead unreachable: {e}"}, 502)
                out = {
                    k: v for k, v in rheaders.items() if k not in _HOP_HEADERS
                }
                self.fast_reply(status, data, out)

            do_PUT = _proxy

        return Handler

    # --- zero-copy GET fast path (docs/SERVING.md) -----------------------
    # Workers previously left every GET on the threaded arm: only the
    # lead's listener carried a resolver, so under `-workers N` just
    # 1-in-N connections could be served from C. This resolver runs the
    # SAME shared plan core against the worker's SharedReadVolume view
    # (idx-tail refresh first, same as _serve_blob), so every
    # SO_REUSEPORT sibling answers hot GETs — and If-None-Match 304s —
    # without leaving its C epoll loop.
    def _make_fast_resolver(self):
        from seaweedfs_tpu.server.volume_server import make_needle_plan_core
        from seaweedfs_tpu.util.httpd import reply_prefix

        plan_core = make_needle_plan_core()
        prefix_304 = reply_prefix(304)
        json_404 = reply_prefix(404) + JSON_HDR
        # the worker's threaded arm 404s with JSON bodies (unlike the
        # lead's empty 404), and distinguishes cookie mismatch — the C
        # arm must serve those exact bytes. No etag on either: a 404
        # can never answer a conditional, matching _serve_blob.
        not_found = (404, json_404, b'{"error": "not found"}',
                     -1, 0, 0, None, prefix_304, 0, 0)
        cookie_404 = (404, json_404, b'{"error": "cookie mismatch"}',
                      -1, 0, 0, None, prefix_304, 0, 0)
        worker = self

        def resolver(path, rng, head_only):
            adm = worker.admission
            if adm is not None and not getattr(adm, "shared", False):
                # per-process rate/N buckets live in the dispatch
                # funnel only; declining routes every request through
                # it. The SHARED (shm) bucket is charged by the C loop
                # itself, so the fast path stays native.
                return None
            if "?" in path:
                return None
            fid_part = path.lstrip("/")
            if "," not in fid_part or "/" in fid_part:
                return None  # UI/status/admin surface proxies the lead
            try:
                fid = FileId.parse(fid_part)
            except ValueError:
                return None
            srv = worker._find_volume(fid.volume_id)
            if srv is None:
                return None  # unknown/EC/mid-commit: proxy decides
            try:
                with srv._lock:
                    srv._refresh()
                # plans are NEVER cacheable here (gen 0, cacheable 0):
                # the lead (and shard siblings) append from other
                # processes, invisible to this process's generation
                # counter — every request must re-run the refresh
                out = plan_core(srv._vol, fid, rng, head_only, 0, 0)
            except (OSError, ValueError, RuntimeError):
                return None  # reopen straddling a vacuum commit:
                # the threaded arm retries with a fresh pair
            if out is None:
                return None
            if out[0] == "notfound":
                return not_found
            if out[0] == "cookie":
                return cookie_404
            return out[1]

        return resolver

    # --- lifecycle --------------------------------------------------------
    def start(self) -> None:
        from seaweedfs_tpu.util.httpd import ReusePortWeedHTTPServer

        handler = self._make_handler()
        if self.shard_writes and self.internal_port:
            # the release/control listener must be up BEFORE any public
            # write can arrive: the lead treats connection-refused on a
            # release call as "worker dead" and takes the vid over —
            # accepting public writes first would race that takeover
            self._internal_server = WeedHTTPServer(
                ("127.0.0.1", self.internal_port), handler
            )
            self._servers.append(self._internal_server)
        if self.shard_writes:
            self._load_taken_vids()
        srv = ReusePortWeedHTTPServer((self.host, self.port), handler)
        self._servers.append(srv)
        if self.worker_port:
            self._servers.append(
                WeedHTTPServer((self.host, self.worker_port), handler)
            )
        # zero-copy GET fast path on every public listener: without
        # this only the lead's 1-in-N share of SO_REUSEPORT accepts
        # ever reached serve.c (docs/SERVING.md). The internal
        # release/control listener stays resolver-less — it is a
        # lead↔worker write/admin hop, never a data-plane GET.
        fast_resolver = self._make_fast_resolver()
        for s in self._servers:
            if s is not self._internal_server:
                s.fast_resolver = fast_resolver
        for s in self._servers:
            # tracing plane: worker hops are spans too, labeled so a
            # shard-hop write reads worker→lead→replica in one trace
            s.trace_name = "worker"
            s.trace_node = f"{self.host}:{self.port}#w{self.writer_index}"
            # admission gates the PUBLIC surfaces only: the internal
            # release/control listener is a trusted lead↔worker hop —
            # shedding it could wedge an ownership handback mid-admin-op
            if s is not self._internal_server:
                s.admission = self.admission
            t = threading.Thread(target=s.serve_forever, daemon=True)
            t.start()
            self._threads.append(t)
        # telemetry plane: workers serve /debug/profile too — a GIL
        # stall in one SO_REUSEPORT process is invisible from the lead
        from seaweedfs_tpu.telemetry import profiler

        profiler.ensure_started()
        wlog.info(
            "volume %s worker %d on %s:%d (lead %s)",
            "write" if self.shard_writes else "read",
            self.writer_index,
            self.host,
            self.port,
            self.lead,
        )

    def _load_taken_vids(self) -> None:
        """Vids the lead already took over (e.g. a takeover while this
        worker was starting) must never be written here."""
        import json
        import urllib.request

        try:
            # weedlint: ignore[no-deadline] — boot-time localhost hop to the lead, 10 s cap; runs before any request deadline can exist
            with urllib.request.urlopen(
                f"http://{self.lead}/__shard/taken", timeout=10
            ) as r:
                taken = json.loads(r.read())
        except (OSError, ValueError):
            return  # lead not up yet: it cannot have taken anything over
        with self._release_lock:
            self.released.update(int(v) for v in taken)

    def stop(self) -> None:
        for s in self._servers:
            s.shutdown()
            s.server_close()
        self._servers.clear()
        # the volume-table drain takes _vol_lock like every other
        # mutation of _volumes: a handler thread finishing its last
        # response can still be inside _find_volume when stop() runs
        # (weedlint unguarded-write finding, OPERATIONS.md round 9)
        with self._vol_lock:
            volumes = list(self._volumes.values())
            self._volumes.clear()
        for v in volumes:
            try:
                v.close()
            except OSError:
                pass


def spawn_read_workers(
    n: int,
    directories: list[str],
    host: str,
    port: int,
    lead_internal: str,
    worker_port_base: int = 0,
    shard_writes: bool = False,
    n_writers: int = 1,
    master: str = "",
    internal_base: int = 0,
    admission_rate: float = 0.0,
    admission_burst: float = 0.0,
    admission_inflight: int = 0,
    admission_procs: int = 1,
    admission_shm_path: str = "",
    commit_window_us: int = 0,
    commit_bytes: int = 4 << 20,
    commit_batch: int = 64,
    commit_fsync: bool = False,
) -> list:
    """Lead-side helper: launch n worker subprocesses sharing host:port
    (writer indices 1..n; the lead is writer 0). Returns the Popen
    handles (terminate them on shutdown)."""
    import subprocess
    import sys

    procs = []
    for k in range(n):
        cmd = [
            sys.executable,
            "-m",
            "seaweedfs_tpu",
            "volume.worker",
            "-ip",
            host,
            "-port",
            str(port),
            "-dir",
            ",".join(directories),
            "-lead",
            lead_internal,
        ]
        if worker_port_base:
            cmd += ["-workerPort", str(worker_port_base + k)]
        if admission_rate > 0 or admission_inflight > 0:
            # with a shm path every member charges ONE shared bucket;
            # without it each enforces 1/procs of the per-client
            # budget — the legacy SO_REUSEPORT sibling convention
            cmd += [
                "-admissionRate", str(admission_rate),
                "-admissionBurst", str(admission_burst),
                "-admissionInflight", str(admission_inflight),
                "-admissionProcs", str(admission_procs),
            ]
            if admission_shm_path:
                cmd += ["-admissionShmPath", admission_shm_path]
        if shard_writes:
            cmd += [
                "-shardWrites",
                "-writerIndex", str(k + 1),
                "-writers", str(n_writers),
                "-internalPort", str(internal_base + k + 1),
            ]
            if master:
                cmd += ["-mserver", master]
            if commit_window_us > 0 or commit_fsync:
                cmd += [
                    "-commitWindowUs", str(commit_window_us),
                    "-commitBytes", str(commit_bytes),
                    "-commitBatch", str(commit_batch),
                ]
                if commit_fsync:
                    cmd += ["-commitFsync"]
        procs.append(subprocess.Popen(cmd))
    return procs
