"""SO_REUSEPORT read workers: per-core scaling for the volume data plane.

The reference volume server scales across cores for free — Go
schedules request goroutines onto every CPU (bazil-style concurrency
behind weed/server/volume_server_handlers_read.go). A CPython process
is pinned to one core by the GIL, so `volume -workers N` spawns N-1
extra *read worker* processes that share the SAME host:port through
SO_REUSEPORT (the kernel distributes accepted connections across the
listeners — the mechanism nginx/envoy use for per-core workers):

  * worker processes serve plain GET/HEAD straight from the shared
    volume directories — each opens the volumes read-only and keeps
    its needle map fresh by replaying the append-only `.idx` tail
    (one fstat per lookup; an inode change means the lead vacuumed
    the volume, which triggers a clean reopen);
  * everything else — writes, deletes, EC/chunk-manifest reads, the
    UI/status pages, image resizing — is proxied over a pooled
    keep-alive connection to the lead's internal listener, so the
    whole surface stays available on every accepted connection;
  * the LEAD (worker 0) remains the one full volume server: it owns
    all writes (single-writer per volume, like the reference), runs
    the gRPC admin plane, and sends the heartbeats. Its inventory
    covers the shared directories, so the master sees one data node.

Read-your-writes holds because the lead appends the `.idx` entry (and
flushes it) before replying 201, and workers re-check the idx size on
every lookup miss-or-hit cycle. Vacuum is safe because a worker keeps
serving the old inode until the commit renames land, then reopens.
"""

from __future__ import annotations

import os
import socket
import threading

from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage.disk_location import parse_volume_file_name
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import Needle  # noqa: F401 (re-export for tests)
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NeedleNotFound,
    Volume,
)
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.httpd import (
    JSON_HDR,
    FastRequestMixin,
    WeedHTTPServer,
    fast_query,
)

from http.server import BaseHTTPRequestHandler

_HOP_HEADERS = {
    "connection",
    "keep-alive",
    "transfer-encoding",
    "content-length",
    "host",
}


class SharedReadVolume:
    """A read-only view of a volume whose writer lives in the lead
    process, kept fresh from the on-disk `.idx` (see module docstring)."""

    def __init__(self, directory: str, vid: int, collection: str = ""):
        self.directory = directory
        self.vid = vid
        self.collection = collection
        self._lock = threading.Lock()
        self._open()

    _ENTRY = 16  # NEEDLE_MAP_ENTRY_SIZE

    def _open(self) -> None:
        from seaweedfs_tpu.storage.volume import volume_base_name

        # stat BEFORE loading: entries appended between the stat and
        # the load replay twice, which is safe (idx replay is last-wins
        # idempotent; metrics are lead-owned). Statting after would
        # skip the [loaded, stat] window forever.
        self._idx_path = (
            volume_base_name(self.directory, self.collection, self.vid) + ".idx"
        )
        st = os.stat(self._idx_path)
        self._idx_ino = st.st_ino
        self._replayed = st.st_size - (st.st_size % self._ENTRY)
        self._vol = Volume(self.directory, self.vid, self.collection, create=False)

    def _refresh(self) -> None:
        st = os.stat(self._idx_path)
        if st.st_ino != self._idx_ino:
            # vacuum/compact committed: whole new .dat/.idx pair
            old = self._vol
            self._open()
            old.close()
            return
        if st.st_size > self._replayed:
            with open(self._idx_path, "rb") as f:
                f.seek(self._replayed)
                tail = f.read(st.st_size - self._replayed)
            # whole entries only: a read racing the lead's 16-byte
            # append may end mid-entry, and advancing past those bytes
            # would shift every later decode
            usable = len(tail) - (len(tail) % self._ENTRY)
            for key, offset, size in idx_codec.iter_entries(tail[:usable]):
                self._vol.nm._replay(key, offset, size)
            self._replayed += usable

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        with self._lock:
            self._refresh()
        return self._vol.read_needle(needle_id, cookie=cookie)

    def close(self) -> None:
        self._vol.close()


class VolumeReadWorker:
    """One worker process: shared-port listener + blob read fast path."""

    def __init__(
        self,
        directories: list[str],
        host: str,
        port: int,
        lead: str,
        worker_port: int = 0,
    ):
        self.directories = directories
        self.host = host
        self.port = port
        self.lead = lead  # host:port of the lead's internal listener
        self.worker_port = worker_port  # optional private listener (tests)
        self._volumes: dict[int, SharedReadVolume] = {}
        self._vol_lock = threading.Lock()
        self._servers: list[WeedHTTPServer] = []
        self._threads: list[threading.Thread] = []

    # --- volume discovery ------------------------------------------------
    def _find_volume(self, vid: int) -> SharedReadVolume | None:
        v = self._volumes.get(vid)
        if v is not None:
            return v
        with self._vol_lock:
            v = self._volumes.get(vid)
            if v is not None:
                return v
            for d in self.directories:
                try:
                    names = os.listdir(d)
                except OSError:
                    continue
                for name in names:
                    parsed = parse_volume_file_name(name)
                    if parsed is None or parsed[1] != vid:
                        continue
                    try:
                        v = SharedReadVolume(d, vid, parsed[0])
                    except (OSError, ValueError, RuntimeError):
                        # unreadable, mid-commit, or remote-tiered
                        # (workers carry no backend config) — the lead
                        # serves it via the proxy path
                        return None
                    self._volumes[vid] = v
                    return v
        return None

    def _drop_volume(self, vid: int) -> None:
        with self._vol_lock:
            v = self._volumes.pop(vid, None)
        if v is not None:
            try:
                v.close()
            except OSError:
                pass

    # --- HTTP ------------------------------------------------------------
    def _make_handler(self):
        worker = self

        class Handler(FastRequestMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                path, _, qs = self.path.partition("?")
                fid_part = path.lstrip("/")
                if "," in fid_part and "/" not in fid_part:
                    q = fast_query(qs)
                    if not ("width" in q or "height" in q):
                        try:
                            fid = FileId.parse(fid_part)
                        except ValueError:
                            fid = None
                        if fid is not None and self._serve_blob(fid):
                            return
                self._proxy()

            do_HEAD = do_GET

            def _serve_blob(self, fid) -> bool:
                """True when served locally; False = hand to the proxy
                (unknown volume, EC volume, chunk manifest, expired…)."""
                v = worker._find_volume(fid.volume_id)
                if v is None:
                    return False
                try:
                    n = v.read_needle(fid.key, cookie=fid.cookie)
                except FileNotFoundError:
                    worker._drop_volume(fid.volume_id)
                    return False
                except CookieMismatch:
                    self._json({"error": "cookie mismatch"}, 404)
                    return True
                except NeedleNotFound:
                    self._json({"error": "not found"}, 404)
                    return True
                except (OSError, ValueError, RuntimeError):
                    worker._drop_volume(fid.volume_id)
                    return False
                if n.is_chunked_manifest():
                    return False  # manifest fan-in needs the lead's store
                if (
                    n.is_gzipped()
                    or n.has_pairs()
                    or self.headers.get("etag-md5") == "True"
                ):
                    # content-encoding negotiation, pair headers, and the
                    # md5-validator variant live in the lead's full
                    # read handler
                    return False
                if n.has_last_modified_date():
                    ims = self.headers.get("if-modified-since")
                    if ims:
                        from email.utils import parsedate_to_datetime

                        try:
                            t = parsedate_to_datetime(ims).timestamp()
                        except (TypeError, ValueError):
                            t = None
                        if t is not None and t >= n.last_modified:
                            self.fast_reply(304)
                            return True
                etag = f'"{n.etag()}"'
                if self.headers.get("if-none-match") == etag:
                    self.fast_reply(304)
                    return True
                headers = {
                    "ETag": etag,
                    "Content-Type": "application/octet-stream",
                    "Accept-Ranges": "bytes",
                }
                if n.has_mime() and n.mime:
                    headers["Content-Type"] = n.mime.decode("latin-1")
                if n.has_name() and n.name:
                    headers["Content-Disposition"] = (
                        f'inline; filename="{n.name.decode("latin-1")}"'
                    )
                if n.has_last_modified_date():
                    from seaweedfs_tpu.server.volume_server import _http_date

                    headers["Last-Modified"] = _http_date(n.last_modified)
                data = n.data
                from seaweedfs_tpu.util.http_range import (
                    RangeNotSatisfiable,
                    parse_range,
                )

                try:
                    span = parse_range(self.headers.get("range", ""), len(data))
                except RangeNotSatisfiable:
                    self.fast_reply(
                        416, b"", {"Content-Range": f"bytes */{len(data)}"}
                    )
                    return True
                if span is None:
                    self.fast_reply(200, data, headers)
                else:
                    start, end = span
                    headers["Content-Range"] = (
                        f"bytes {start}-{end}/{len(data)}"
                    )
                    self.fast_reply(206, data[start : end + 1], headers)
                return True

            def _json(self, obj, status=200):
                import json

                self.fast_reply(status, json.dumps(obj).encode(), JSON_HDR)

            def _proxy(self):
                """Forward this request verbatim to the lead and relay
                the response (one pooled keep-alive conn per handler
                thread, via the client transport)."""
                from seaweedfs_tpu.client.operation import _drop_conn, _pooled_conn

                body = None
                if self.command in ("POST", "PUT", "DELETE"):
                    try:
                        n = int(self.headers.get("content-length", "0"))
                    except ValueError:
                        n = 0
                    body = self.rfile.read(n) if n else b""
                fwd = {
                    k: v
                    for k, v in self.headers.items()
                    if k not in _HOP_HEADERS
                }
                try:
                    c, reused = _pooled_conn(worker.lead, 30.0)
                    try:
                        c.send_request(self.command, self.path, body, fwd)
                        status, rheaders, data, will_close = c.read_response(
                            self.command
                        )
                    except OSError:
                        _drop_conn(worker.lead)
                        if not reused:
                            raise
                        c, _ = _pooled_conn(worker.lead, 30.0)
                        c.send_request(self.command, self.path, body, fwd)
                        status, rheaders, data, will_close = c.read_response(
                            self.command
                        )
                    if will_close:
                        _drop_conn(worker.lead)
                except OSError as e:
                    return self._json({"error": f"lead unreachable: {e}"}, 502)
                out = {
                    k: v for k, v in rheaders.items() if k not in _HOP_HEADERS
                }
                self.fast_reply(status, data, out)

            do_POST = _proxy
            do_DELETE = _proxy
            do_PUT = _proxy

        return Handler

    # --- lifecycle --------------------------------------------------------
    def start(self) -> None:
        from seaweedfs_tpu.util.httpd import ReusePortWeedHTTPServer

        handler = self._make_handler()
        srv = ReusePortWeedHTTPServer((self.host, self.port), handler)
        self._servers.append(srv)
        if self.worker_port:
            self._servers.append(
                WeedHTTPServer((self.host, self.worker_port), handler)
            )
        for s in self._servers:
            t = threading.Thread(target=s.serve_forever, daemon=True)
            t.start()
            self._threads.append(t)
        wlog.info(
            "volume read worker on %s:%d (lead %s)", self.host, self.port, self.lead
        )

    def stop(self) -> None:
        for s in self._servers:
            s.shutdown()
            s.server_close()
        self._servers.clear()
        for v in list(self._volumes.values()):
            try:
                v.close()
            except OSError:
                pass
        self._volumes.clear()


def spawn_read_workers(
    n: int,
    directories: list[str],
    host: str,
    port: int,
    lead_internal: str,
    worker_port_base: int = 0,
) -> list:
    """Lead-side helper: launch n worker subprocesses sharing host:port.
    Returns the Popen handles (terminate them on shutdown)."""
    import subprocess
    import sys

    procs = []
    for k in range(n):
        cmd = [
            sys.executable,
            "-m",
            "seaweedfs_tpu",
            "volume.worker",
            "-ip",
            host,
            "-port",
            str(port),
            "-dir",
            ",".join(directories),
            "-lead",
            lead_internal,
        ]
        if worker_port_base:
            cmd += ["-workerPort", str(worker_port_base + k)]
        procs.append(subprocess.Popen(cmd))
    return procs
