import sys

from seaweedfs_tpu.command import main

sys.exit(main())
