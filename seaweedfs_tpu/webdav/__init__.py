from seaweedfs_tpu.webdav.webdav_server import WebDavServer

__all__ = ["WebDavServer"]
