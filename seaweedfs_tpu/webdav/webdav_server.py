"""WebDAV gateway over the filer.

Behavioral match of weed/server/webdav_server.go:44-93, which adapts
golang.org/x/net/webdav's FileSystem interface onto filer gRPC. With no
webdav library in this image the protocol layer is implemented
directly: OPTIONS, PROPFIND (Depth 0/1), MKCOL, GET/HEAD, PUT, DELETE,
MOVE, COPY with 207 multistatus XML — the verb set `cadaver`,
macOS Finder, and davfs2 need. Object bytes ride the filer HTTP path
(auto-chunking), metadata rides filer gRPC, same split as the S3
gateway.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import grpc

from seaweedfs_tpu import trace
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.util.httpd import FastHandler, WeedHTTPServer
from seaweedfs_tpu.pb import rpc

DAV_NS = "DAV:"


class WebDavServer:
    def __init__(
        self,
        filer: str,
        host: str = "127.0.0.1",
        port: int = 7333,
        root: str = "/",
        masters: list[str] | None = None,
        announce_interval: float = 10.0,
        reuse_port: bool = False,
        serve_idle_ms: int = 0,
        serve_max_reqs: int = 0,
        admission_rate: float = 0.0,
        admission_burst: float = 0.0,
        admission_inflight: int = 0,
        admission_procs: int = 1,
        admission_shm_path: str = "",
    ):
        self.filer = filer
        self.host = host
        self.port = port
        self.root = root.rstrip("/")
        # telemetry plane: masters to announce this gateway to so the
        # cluster collector can scrape it (empty = no announce)
        self.masters = list(masters or [])
        self.announce_interval = announce_interval
        # `webdav -serveProcs N`: SO_REUSEPORT accept-process group +
        # keep-alive knobs (docs/SERVING.md)
        self.reuse_port = reuse_port
        self.serve_idle_ms = serve_idle_ms
        self.serve_max_reqs = serve_max_reqs
        # QoS plane (docs/QOS.md): per-client admission control keyed
        # by remote address (WebDAV carries no access keys); budgets
        # split across the -serveProcs group like the S3 gateway's
        self.admission = None
        if admission_rate > 0 or admission_inflight > 0:
            from seaweedfs_tpu.qos.admission import AdmissionController

            self.admission = AdmissionController(
                rate=admission_rate,
                burst=admission_burst,
                max_inflight=admission_inflight,
                procs=admission_procs,
                label="webdav",
                shm_path=admission_shm_path,
            )
        self._announce: threading.Thread | None = None
        self._http_server: WeedHTTPServer | None = None
        self._channel: grpc.Channel | None = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def _stub(self):
        with self._lock:
            if self._channel is None:
                self._channel = rpc.dial(rpc.grpc_address(self.filer))
            return rpc.filer_stub(self._channel)

    def _full(self, dav_path: str) -> str:
        path = self.root + "/" + dav_path.strip("/")
        return path.rstrip("/") or "/"

    def _lookup(self, full_path: str):
        directory, _, name = full_path.rpartition("/")
        if not name:
            # the namespace root always exists as a collection
            return fpb.Entry(name="/", is_directory=True)
        try:
            return self._stub().LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(
                    directory=directory or "/", name=name
                )
            ).entry
        except grpc.RpcError:
            return None

    def _list(self, full_path: str):
        try:
            return [
                r.entry
                for r in self._stub().ListEntries(
                    fpb.ListEntriesRequest(directory=full_path, limit=10000)
                )
            ]
        except grpc.RpcError:
            return []

    def start(self) -> None:
        if self.reuse_port:
            from seaweedfs_tpu.util.httpd import ReusePortWeedHTTPServer

            server_cls = ReusePortWeedHTTPServer
        else:
            server_cls = WeedHTTPServer
        self._http_server = server_cls(
            (self.host, self.port), self._handler_class()
        )
        self._http_server.serve_idle_ms = self.serve_idle_ms
        self._http_server.serve_max_reqs = self.serve_max_reqs
        # tracing + metrics plane: span per request, request counters/
        # histograms under "webdav", and /metrics exposition (the
        # gateway exposed nothing before)
        self._http_server.trace_name = "webdav"
        self._http_server.trace_node = f"{self.host}:{self.port}"
        self._http_server.gateway_metrics = True
        self._http_server.admission = self.admission
        threading.Thread(
            target=self._http_server.serve_forever, daemon=True, name="webdav-http"
        ).start()
        from seaweedfs_tpu.telemetry import profiler
        from seaweedfs_tpu.telemetry.announce import start_announce_loop

        profiler.ensure_started()
        self._announce = start_announce_loop(
            "webdav", f"{self.host}:{self.port}", self.masters,
            interval=self.announce_interval,
        )

    def stop(self) -> None:
        if self._announce is not None:
            self._announce.stop_event.set()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._channel is not None:
            self._channel.close()

    # ------------------------------------------------------------------
    def _handler_class(self):
        server = self

        class Handler(FastHandler):
            # DAV verbs (PROPFIND/MKCOL/MOVE/...) ride the mini request
            # loop's dict dispatch exactly like GET/PUT — the loop's
            # do_* table is built from dir(handler), not a verb list

            def _send(self, status: int, body: bytes = b"", headers: dict | None = None):
                self.fast_reply(status, body, headers)

            def _dav_path(self) -> str:
                return urllib.parse.unquote(urllib.parse.urlparse(self.path).path)

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or "0")
                return self.rfile.read(n) if n else b""

            # ---------------- verbs ----------------
            def do_OPTIONS(self):
                self._send(
                    200,
                    headers={
                        "DAV": "1,2",
                        "MS-Author-Via": "DAV",
                        "Allow": "OPTIONS, PROPFIND, MKCOL, GET, HEAD, PUT, "
                        "DELETE, MOVE, COPY, PROPPATCH, LOCK, UNLOCK",
                    },
                )

            def do_PROPFIND(self):
                self._read_body()  # property filters: we always return the basic set
                dav = self._dav_path()
                full = server._full(dav)
                entry = server._lookup(full)
                if entry is None:
                    return self._send(404)
                depth = self.headers.get("Depth", "1")
                ms = ET.Element("{DAV:}multistatus")
                _add_response(ms, dav, entry)
                if depth != "0" and entry.is_directory:
                    base = dav.rstrip("/")
                    for child in server._list(full):
                        _add_response(ms, f"{base}/{child.name}", child)
                ET.register_namespace("D", DAV_NS)
                body = b'<?xml version="1.0" encoding="utf-8"?>' + ET.tostring(ms)
                self._send(
                    207, body, {"Content-Type": 'application/xml; charset="utf-8"'}
                )

            def do_PROPPATCH(self):
                self._read_body()
                # properties aren't persisted (the reference's webdav FS
                # ignores them too); reply success so clients proceed
                self._send(207, b'<?xml version="1.0"?><D:multistatus xmlns:D="DAV:"/>')

            def do_MKCOL(self):
                dav = self._dav_path()
                full = server._full(dav)
                if server._lookup(full) is not None:
                    return self._send(405)
                directory, _, name = full.rpartition("/")
                try:
                    server._stub().CreateEntry(
                        fpb.CreateEntryRequest(
                            directory=directory or "/",
                            entry=fpb.Entry(
                                name=name,
                                is_directory=True,
                                attributes=fpb.Attributes(
                                    mtime=int(time.time()), file_mode=0o40777
                                ),
                            ),
                        )
                    )
                except grpc.RpcError:
                    return self._send(409)
                self._send(201)

            def do_GET(self):
                dav = self._dav_path()
                full = server._full(dav)
                entry = server._lookup(full)
                if entry is None:
                    return self._send(404)
                if entry.is_directory:
                    names = "\n".join(e.name for e in server._list(full))
                    return self._send(
                        200, names.encode(), {"Content-Type": "text/plain"}
                    )
                req = urllib.request.Request(
                    f"http://{server.filer}{urllib.parse.quote(full)}",
                    # HEAD passes through as HEAD: the filer answers it
                    # from metadata with zero chunk IO, so size probes
                    # on multi-GB files never read the body
                    method=self.command,
                )
                trace.inject_request(req)
                rng = self.headers.get("Range")
                if rng:
                    # WebDAV clients (video players, resumable copies)
                    # issue ranged GETs; the filer serves them natively
                    req.add_header("Range", rng)
                try:
                    # weedlint: ignore[no-deadline] — one bounded 60 s hop to the local filer; ranged Request objects predate the pooled transport
                    with urllib.request.urlopen(req, timeout=60) as r:
                        data = b"" if self.command == "HEAD" else r.read()
                        mime = r.headers.get("Content-Type", "application/octet-stream")
                        headers = {"Content-Type": mime, "Accept-Ranges": "bytes"}
                        if r.status == 206:
                            headers["Content-Range"] = r.headers.get("Content-Range", "")
                        return self._send(r.status, data, headers)
                except urllib.error.HTTPError as e:
                    hdrs = {}
                    if e.code == 416 and e.headers.get("Content-Range"):
                        # the unsatisfiable-range reply must carry the
                        # real size or resumable clients cannot recover
                        hdrs["Content-Range"] = e.headers["Content-Range"]
                    return self._send(e.code, b"", hdrs)

            do_HEAD = do_GET

            def do_PUT(self):
                full = server._full(self._dav_path())
                body = self._read_body()
                req = urllib.request.Request(
                    f"http://{server.filer}{urllib.parse.quote(full)}",
                    data=body,
                    method="POST",
                )
                trace.inject_request(req)
                ct = self.headers.get("Content-Type")
                if ct:
                    req.add_header("Content-Type", ct)
                try:
                    # weedlint: ignore[no-deadline] — one bounded 60 s filer PUT hop; rides the same migration as the GET above
                    urllib.request.urlopen(req, timeout=60).close()
                except urllib.error.HTTPError as e:
                    return self._send(e.code)
                self._send(201)

            def do_DELETE(self):
                full = server._full(self._dav_path())
                entry = server._lookup(full)
                if entry is None:
                    return self._send(404)
                directory, _, name = full.rpartition("/")
                try:
                    server._stub().DeleteEntry(
                        fpb.DeleteEntryRequest(
                            directory=directory or "/",
                            name=name,
                            is_delete_data=True,
                            is_recursive=True,
                        )
                    )
                except grpc.RpcError:
                    return self._send(409)
                self._send(204)

            def do_MOVE(self):
                src = server._full(self._dav_path())
                dst_hdr = self.headers.get("Destination", "")
                dst = server._full(
                    urllib.parse.unquote(urllib.parse.urlparse(dst_hdr).path)
                )
                if server._lookup(src) is None:
                    return self._send(404)
                overwrote = server._lookup(dst) is not None
                sdir, _, sname = src.rpartition("/")
                ddir, _, dname = dst.rpartition("/")
                try:
                    server._stub().AtomicRenameEntry(
                        fpb.AtomicRenameEntryRequest(
                            old_directory=sdir or "/",
                            old_name=sname,
                            new_directory=ddir or "/",
                            new_name=dname,
                        )
                    )
                except grpc.RpcError:
                    return self._send(409)
                self._send(204 if overwrote else 201)

            def do_COPY(self):
                src = server._full(self._dav_path())
                dst_hdr = self.headers.get("Destination", "")
                dst = server._full(
                    urllib.parse.unquote(urllib.parse.urlparse(dst_hdr).path)
                )
                entry = server._lookup(src)
                if entry is None:
                    return self._send(404)
                if entry.is_directory:
                    return self._send(501)  # collection COPY: not supported
                overwrote = server._lookup(dst) is not None
                try:
                    # weedlint: ignore[no-deadline] — COPY source read, one bounded 60 s filer hop
                    with urllib.request.urlopen(
                        f"http://{server.filer}{urllib.parse.quote(src)}", timeout=60
                    ) as r:
                        data = r.read()
                        mime = r.headers.get("Content-Type", "")
                    req = urllib.request.Request(
                        f"http://{server.filer}{urllib.parse.quote(dst)}",
                        data=data,
                        method="POST",
                    )
                    trace.inject_request(req)
                    if mime:
                        req.add_header("Content-Type", mime)
                    # weedlint: ignore[no-deadline] — COPY destination write, one bounded 60 s filer hop
                    urllib.request.urlopen(req, timeout=60).close()
                except urllib.error.HTTPError as e:
                    return self._send(e.code)
                self._send(204 if overwrote else 201)

            def do_LOCK(self):
                # advertise-only locking (class 2 so clients write): hand
                # out an opaque token without server-side state
                token = f"opaquelocktoken:{int(time.time()*1000):x}"
                body = (
                    '<?xml version="1.0" encoding="utf-8"?>'
                    '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                    "<D:locktype><D:write/></D:locktype>"
                    "<D:lockscope><D:exclusive/></D:lockscope>"
                    f"<D:locktoken><D:href>{token}</D:href></D:locktoken>"
                    "</D:activelock></D:lockdiscovery></D:prop>"
                ).encode()
                self._send(
                    200,
                    body,
                    {"Lock-Token": f"<{token}>", "Content-Type": "application/xml"},
                )

            def do_UNLOCK(self):
                self._send(204)

        return Handler





def _add_response(ms: ET.Element, href: str, entry) -> None:
    resp = ET.SubElement(ms, "{DAV:}response")
    is_dir = entry.is_directory
    ET.SubElement(resp, "{DAV:}href").text = urllib.parse.quote(
        href if not is_dir else href.rstrip("/") + "/"
    )
    propstat = ET.SubElement(resp, "{DAV:}propstat")
    prop = ET.SubElement(propstat, "{DAV:}prop")
    rtype = ET.SubElement(prop, "{DAV:}resourcetype")
    if is_dir:
        ET.SubElement(rtype, "{DAV:}collection")
    else:
        size = sum(c.size for c in entry.chunks)
        ET.SubElement(prop, "{DAV:}getcontentlength").text = str(size)
        mime = entry.attributes.mime or "application/octet-stream"
        ET.SubElement(prop, "{DAV:}getcontenttype").text = mime
    mtime = entry.attributes.mtime if entry.attributes else 0
    ET.SubElement(prop, "{DAV:}getlastmodified").text = time.strftime(
        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(mtime or 0)
    )
    ET.SubElement(prop, "{DAV:}displayname").text = entry.name
    ET.SubElement(propstat, "{DAV:}status").text = "HTTP/1.1 200 OK"
