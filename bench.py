"""RS(10,4) erasure-encode throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "ec_encode_rs10_4", "value": <GB/s>, "unit": "GB/s",
   "vs_baseline": <value / 40.0>}

value   = data bytes erasure-coded per second (the bytes of the sealed
          volume stream, i.e. the 10 data shards — same accounting as
          timing the reference's `ec.encode` hot loop, the
          klauspost/reedsolomon AVX2 Encode call at
          weed/storage/erasure_coding/ec_encoder.go:173).
baseline: the repo publishes no EC numbers (BASELINE.md), so the ratio
          is against the 40 GB/s/chip north-star target from
          BASELINE.json; vs_baseline >= 1.0 means target met.

Method: the TPU codec kernel (bitsliced GF(2^8) XOR-matmul,
seaweedfs_tpu/ec/codec_tpu.py) encodes a device-resident [10, N] uint8
volume block stream. Data is generated on-device (no PCIe in the timed
region); each timed iteration produces the [4, N] parity block. One
fixed shape to pay the remote-compile cost once.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # 64 MiB per shard on the real chip (640 MiB data per step);
    # smaller when falling back to CPU so the bench stays quick.
    shard_len = (64 if on_tpu else 4) * 1024 * 1024

    from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

    kern = TpuCodecKernels(10, 4)

    @jax.jit
    def gen(key):
        return jax.random.randint(
            key, (10, shard_len), 0, 256, dtype=jnp.int32
        ).astype(jnp.uint8)

    data = gen(jax.random.PRNGKey(0))
    data.block_until_ready()

    encode = jax.jit(lambda d: kern.encode(d))
    encode(data).block_until_ready()  # compile + warm

    iters = 8 if on_tpu else 2
    start = time.perf_counter()
    for _ in range(iters):
        parity = encode(data)
    parity.block_until_ready()
    elapsed = time.perf_counter() - start

    data_bytes = 10 * shard_len * iters
    gbps = data_bytes / elapsed / 1e9
    print(
        json.dumps(
            {
                "metric": "ec_encode_rs10_4",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / 40.0, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
